"""Front door (ISSUE 16): tenant router, SLO-burn autoscaler, AOT cache.

The contracts under test:

- **stickiness is stateless**: rendezvous hashing gives every router
  instance (and every restart) the identical tenant→peer map; removing a
  non-owner peer never moves a tenant.
- **spill is a preference override, not a cage**: a shed / not-ready /
  burn-red owner spills to the least-loaded OTHER ready peer; with nobody
  to spill to, the owner's own admission plane is the backstop.
- **evict-vs-route race** (the WarmState regression): while a router
  heartbeat is fresh, a group key routed-to within the grace window
  survives the idle-TTL sweep (deferred, not exempted).
- **the AOT cache can only ever cost a rejected read**: corrupt, torn and
  version-mismatched entries are rejected (``aot.reject``) and the cold
  path answers; a published entry round-trips into a FRESH process
  byte-identical to the cold compile (slow arm).
- **exactly-once through the front door**: a peer SIGKILLed mid-job is
  routed around; the client's retry with the SAME idempotency key lands on
  the survivor exactly once, byte-identical to the solo run.
"""

import hashlib
import json
import os
import pickle
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from daccord_tpu.sim import SimConfig, make_dataset

try:
    from daccord_tpu.native import available as _native_available

    HAVE_NATIVE = _native_available()
except Exception:
    HAVE_NATIVE = False

needs_native = pytest.mark.skipif(not HAVE_NATIVE,
                                  reason="native host path unavailable")


class _CapLog:
    """Capture logger matching the obs logger surface."""

    def __init__(self):
        self.events = []

    def log(self, event, **kw):
        self.events.append((event, kw))

    def __getitem__(self, name):
        return [kw for ev, kw in self.events if ev == name]

    def close(self):
        pass


def _lint(paths):
    from daccord_tpu.tools.eventcheck import validate_events

    for p in paths:
        errs = validate_events(p, strict=True)
        assert not errs, (p, errs[:5])


def _mk_peer(name, **kw):
    from daccord_tpu.serve.router import Peer

    kw.setdefault("alive", True)
    kw.setdefault("ready", True)
    return Peer(name=name, url=kw.pop("url", f"http://127.0.0.1:1/{name}"),
                **kw)


def _mk_router(tmp_path, **kw):
    """A Router with its poll thread effectively parked (tests drive
    refresh()/route() directly for determinism)."""
    from daccord_tpu.serve.router import Router, RouterConfig

    kw.setdefault("poll_s", 3600.0)
    kw.setdefault("peer_dir", str(tmp_path / "fleet"))
    kw.setdefault("workdir", str(tmp_path / "router"))
    os.makedirs(kw["peer_dir"], exist_ok=True)
    return Router(RouterConfig(**kw))


# ---------------------------------------------------------------------------
# routing policy units
# ---------------------------------------------------------------------------

def test_rendezvous_owner_deterministic_and_stable(tmp_path):
    rt = _mk_router(tmp_path)
    try:
        names = ["peer-a", "peer-b", "peer-c", "peer-d"]
        peers = [_mk_peer(n) for n in names]
        tenants = [f"tenant{i}" for i in range(40)]
        owners = {t: rt.owner_of(t, peers).name for t in tenants}
        # a second pass (and a "restarted router" = a fresh instance) maps
        # identically: the stickiness is pure hash, no state to lose
        assert {t: rt.owner_of(t, peers).name for t in tenants} == owners
        # every peer owns someone (4 peers, 40 tenants: astronomically
        # unlikely to miss one unless the hash is broken)
        assert set(owners.values()) == set(names)
        # rendezvous minimal-disruption: dropping a NON-owner peer never
        # moves a tenant
        for t in tenants:
            for drop in names:
                if drop == owners[t]:
                    continue
                rest = [p for p in peers if p.name != drop]
                assert rt.owner_of(t, rest).name == owners[t], (t, drop)
        # readiness does NOT move ownership (route() spills off a not-ready
        # owner; the map itself must stay put while a peer warms)
        peers[0].ready = False
        assert {t: rt.owner_of(t, peers).name for t in tenants} == owners
        # dead peers DO: ownership is computed over alive peers only
        peers[0].alive = False
        assert all(rt.owner_of(t, peers).name != "peer-a" for t in tenants)
        for p in peers:
            p.alive = False
        assert rt.owner_of("tenant0", peers) is None
    finally:
        rt.shutdown()
    _lint([os.path.join(str(tmp_path / "router"), "router.events.jsonl")])


def test_route_spills_on_shed_notready_and_burn(tmp_path):
    rt = _mk_router(tmp_path, spill_burn=1.0)
    try:
        a, b, c = _mk_peer("pa"), _mk_peer("pb"), _mk_peer("pc")
        rt.peers = {"pa": a, "pb": b, "pc": c}
        tenant = next(t for t in (f"t{i}" for i in range(1000))
                      if rt.owner_of(t).name == "pa")
        assert rt.route(tenant).name == "pa"          # healthy owner: sticky

        # shed owner spills to the least-loaded OTHER ready peer
        a.shed_level = 1
        b.jobs_active, c.jobs_active = 5, 1
        assert rt.route(tenant).name == "pc"
        # burn tie-breaks equal queue loads
        b.jobs_active = c.jobs_active = 2
        b.burn, c.burn = 0.1, 0.9
        assert rt.route(tenant).name == "pb"
        a.shed_level = 0

        # not-ready owner spills
        a.ready = False
        assert rt.route(tenant).name in ("pb", "pc")
        a.ready = True

        # burn-red owner spills; below the band it does not
        a.burn = 2.0
        assert rt.route(tenant).name != "pa"
        a.burn = 0.5
        assert rt.route(tenant).name == "pa"

        # nobody to spill to: the shedding owner still beats a refusal
        a.shed_level = 2
        rt.peers = {"pa": a}
        assert rt.route(tenant).name == "pa"
        # empty fleet: route refuses
        rt.peers = {}
        assert rt.route(tenant) is None

        spills = [kw for ev, kw in
                  ((e["event"], e) for e in _events(rt))
                  if ev == "router.spill"]
        assert {s["reason"] for s in spills} == {"shed", "not_ready", "burn"}
        assert all(s["owner"] == "pa" for s in spills)
        assert rt.counters["spills"] == len(spills)
    finally:
        rt.shutdown()
    _lint([os.path.join(str(tmp_path / "router"), "router.events.jsonl")])


def _events(rt):
    rt.log.flush()
    path = os.path.join(rt.cfg.workdir, "router.events.jsonl")
    with open(path) as fh:
        return [json.loads(l) for l in fh if l.strip()]


# ---------------------------------------------------------------------------
# discovery: announce leases + healthz polls
# ---------------------------------------------------------------------------

class _FakeHealthz(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802
        self.server.router_headers.append(
            self.headers.get("X-Daccord-Router"))
        body = json.dumps(self.server.payload).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # noqa: A002
        pass


def test_discovery_announce_up_down(tmp_path):
    from daccord_tpu.utils import lease

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _FakeHealthz)
    httpd.daemon_threads = True
    httpd.payload = {"ok": True, "ready": True, "shed_level": 1,
                     "queue_depth": 3, "burn": 0.25,
                     "jobs": {"queued": 2, "running": 1}}
    httpd.router_headers = []
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"

    rt = _mk_router(tmp_path, lease_ttl_s=5.0)
    fleet = rt.cfg.peer_dir
    os.makedirs(os.path.join(fleet, "peers"), exist_ok=True)
    lp = os.path.join(fleet, "peers", "peer-x.lease")
    lease.claim(lp, "peer-x@test", 5.0, extra={"url": url,
                                               "service": "peer-x"})
    try:
        rt.refresh()
        p = rt.peers["peer-x"]
        assert p.alive and p.ready and p.shed_level == 1
        assert p.queue_depth == 3 and p.burn == 0.25 and p.jobs_active == 3
        # the poll arms the peers' evict-vs-route grace window
        assert httpd.router_headers and httpd.router_headers[0] == "1"

        # healthz death: peer stays discovered (lease fresh) but down
        httpd.shutdown()
        rt.refresh()
        assert "peer-x" in rt.peers and not rt.peers["peer-x"].alive
        assert rt.owner_of("anyone") is None

        # stale announce: the peer vanishes from the table entirely
        lease.backdate(lp, 60.0)
        rt.refresh()
        assert "peer-x" not in rt.peers

        evs = _events(rt)
        ups = [e for e in evs if e["event"] == "router.peer_up"]
        downs = [e for e in evs if e["event"] == "router.peer_down"]
        assert ups and ups[0]["peer"] == "peer-x" and ups[0]["ready"]
        assert downs and downs[0]["reason"] == "healthz"
    finally:
        rt.shutdown()
        httpd.server_close()
    _lint([os.path.join(str(tmp_path / "router"), "router.events.jsonl")])


# ---------------------------------------------------------------------------
# WarmState evict-vs-route regression (ISSUE 16 bugfix)
# ---------------------------------------------------------------------------

class _FakeGroup:
    def __init__(self, name):
        self.name = name
        self.refs = 0
        self.last_used = 0.0
        self.closed = False

    def close(self):
        self.closed = True

    def stats(self):
        return {"name": self.name}


def test_warmstate_defers_eviction_for_routed_key():
    from daccord_tpu.serve.state import WarmState

    log = _CapLog()
    ws = WarmState(idle_evict_s=10.0, log=log, route_grace_s=30.0)
    g = ws.acquire("k1", lambda: _FakeGroup("g1"))
    ws.release("k1")
    idle_at = g.last_used + 11.0          # past the TTL, refs == 0

    # the race: the router's stickiness points here (fresh heartbeat +
    # recent route stamp) — the sweep must defer, not evict the exact
    # group the next submit is about to hit
    ws.note_router_heartbeat(now=idle_at - 1.0)
    ws.note_route("k1", now=idle_at - 5.0)
    assert ws.evict_idle(now=idle_at) == 0
    assert not g.closed and ws.counters["evict_deferred"] == 1
    defer = log["serve.evict_defer"]
    assert defer and defer[0]["group"] == "g1" \
        and defer[0]["routed_s"] == pytest.approx(5.0)

    # grace lapsed (router still alive): the TTL wins again
    late = idle_at + 40.0
    ws.note_router_heartbeat(now=late - 1.0)
    assert ws.evict_idle(now=late) == 1 and g.closed
    assert ws.counters["evicted"] == 1


def test_warmstate_evicts_when_no_router_or_no_route():
    from daccord_tpu.serve.state import WarmState

    ws = WarmState(idle_evict_s=10.0, route_grace_s=30.0)
    # no heartbeat ever: plain TTL behaviour (solo deployments unchanged)
    g1 = ws.acquire("k1", lambda: _FakeGroup("g1"))
    ws.release("k1")
    assert ws.evict_idle(now=g1.last_used + 11.0) == 1 and g1.closed

    # routed recently but the router DIED (stale heartbeat): grace disarms
    g2 = ws.acquire("k2", lambda: _FakeGroup("g2"))
    ws.release("k2")
    idle_at = g2.last_used + 11.0
    ws.note_router_heartbeat(now=idle_at - 60.0)
    ws.note_route("k2", now=idle_at - 1.0)
    assert ws.evict_idle(now=idle_at) == 1 and g2.closed

    # router alive but the key was never routed to: evicted
    g3 = ws.acquire("k3", lambda: _FakeGroup("g3"))
    ws.release("k3")
    idle_at = g3.last_used + 11.0
    ws.note_router_heartbeat(now=idle_at - 1.0)
    assert ws.evict_idle(now=idle_at) == 1 and g3.closed
    assert ws.counters["evict_deferred"] == 0


# ---------------------------------------------------------------------------
# AOT cache: reject taxonomy (no compile needed — synthetic entries)
# ---------------------------------------------------------------------------

def _write_entry(cache, key, digest, body: bytes, sha: bytes | None = None):
    from daccord_tpu.serve.aotcache import _MAGIC

    sha = hashlib.sha256(body).digest() if sha is None else sha
    with open(cache._path(key, digest), "wb") as fh:
        fh.write(_MAGIC + sha + body)


def test_aot_rejects_corrupt_torn_and_version_mismatch(tmp_path):
    from daccord_tpu.serve.aotcache import AotCache, _versions

    log = _CapLog()
    cache = AotCache(str(tmp_path / "aot"), log=log)
    key, digest = "cpu:B8xD8xL32", "0" * 16
    good = pickle.dumps({"key": key, "meta": _versions(),
                         "payload": b"not-an-executable",
                         "in_tree": None, "out_tree": None})

    # bit-flip: checksum fails → corrupt, never unpickled
    flipped = bytearray(good)
    flipped[-1] ^= 0xFF
    _write_entry(cache, key, digest, bytes(flipped),
                 sha=hashlib.sha256(good).digest())
    assert cache.load(key, digest) is None

    # torn write: shorter than the header → corrupt
    with open(cache._path(key, digest), "wb") as fh:
        fh.write(b"DACAOT01trunc")
    assert cache.load(key, digest) is None

    # checksum-valid garbage that fails deserialization → load:<type>
    _write_entry(cache, key, digest, good)
    assert cache.load(key, digest) is None

    # version-pin mismatch: a different jax/jaxlib/backend is SKIPPED (a
    # stale fleet dir after an upgrade must not poison new peers)
    meta = dict(_versions())
    meta["jax"] = "0.0.0-somethingelse"
    _write_entry(cache, key, digest, pickle.dumps(
        {"key": key, "meta": meta, "payload": b"x",
         "in_tree": None, "out_tree": None}))
    assert cache.load(key, digest) is None

    reasons = [kw["reason"] for kw in log["aot.reject"]]
    assert reasons[:2] == ["corrupt", "corrupt"]
    assert reasons[2].startswith("load:") and reasons[3] == "version"
    assert cache.stats()["rejects"] == 4 and cache.stats()["hits"] == 0


# ---------------------------------------------------------------------------
# AOT cache: real round-trip (slow arm — one XLA compile)
# ---------------------------------------------------------------------------

_SUBPROC_LOAD = r"""
import hashlib, json, os, pickle, sys
os.environ["JAX_PLATFORMS"] = "cpu"
prof, ccfg, lad_kw, batch = pickle.load(open(sys.argv[1], "rb"))
from daccord_tpu.kernels.tiers import TierLadder, fetch
from daccord_tpu.serve.aotcache import AotCache
ladder = TierLadder.from_config(prof, ccfg, **lad_kw)
cache = AotCache(sys.argv[2])
out = fetch(cache.dispatcher(ladder)(batch))
import numpy as np
h = "".join(hashlib.sha256(np.asarray(out[k]).tobytes()).hexdigest()
            for k in ("cons", "cons_len", "solved"))
json.dump({"hash": h, "counters": cache.stats()}, sys.stdout)
"""


def _out_hash(out):
    import numpy as np

    return "".join(hashlib.sha256(np.asarray(out[k]).tobytes()).hexdigest()
                   for k in ("cons", "cons_len", "solved"))


@pytest.mark.slow
def test_aot_roundtrip_fresh_process_and_corrupt_fallback(tmp_path):
    """publish → FRESH-process load → byte-identical vs the cold compile;
    then a corrupted entry falls back to the cold path (and heals it)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from daccord_tpu.kernels import BatchShape, TierLadder, tensorize_windows
    from daccord_tpu.kernels.tiers import fetch
    from daccord_tpu.oracle import (ConsensusConfig, cut_windows,
                                    estimate_profile_two_pass, refine_overlap)
    from daccord_tpu.serve.aotcache import AotCache, static_digest
    from daccord_tpu.sim import simulate

    cfg = SimConfig(genome_len=1200, coverage=10, read_len_mean=400, seed=7)
    res = simulate(cfg)
    aread = max(range(len(res.reads)), key=lambda i: len(res.reads[i].seq))
    a = res.reads[aread].seq
    refined = [refine_overlap(o, a, res.reads[o.bread].seq, cfg.tspace)
               for o in res.overlaps if o.aread == aread]
    ccfg = ConsensusConfig()
    windows = cut_windows(a, refined, w=ccfg.w, adv=ccfg.adv)
    prof = estimate_profile_two_pass(refined, windows, ccfg, sample=8)
    lad_kw = {"max_kmers": 24, "rescue_max_kmers": 48}
    ladder = TierLadder.from_config(prof, ccfg, **lad_kw)
    batch = tensorize_windows([(aread, ws) for ws in windows],
                              BatchShape(depth=16, seg_len=64, wlen=40))

    aot_dir = str(tmp_path / "aot")
    log = _CapLog()
    cache = AotCache(aot_dir, log=log)
    # cold: miss → ONE lower().compile() → publish
    out_cold = fetch(cache.dispatcher(ladder)(batch))
    assert cache.stats()["misses"] == 1 and cache.stats()["publishes"] == 1
    assert log["aot.miss"] and log["aot.publish"]
    entries = [f for f in os.listdir(aot_dir) if f.endswith(".aot")]
    assert len(entries) == 1
    want = _out_hash(out_cold)

    # fresh process: rebuilds the (deterministic) ladder, loads the fleet
    # entry — zero compiles — and answers byte-identically
    pkl = str(tmp_path / "case.pkl")
    with open(pkl, "wb") as fh:
        pickle.dump((prof, ccfg, lad_kw, batch), fh)
    r = subprocess.run([sys.executable, "-c", _SUBPROC_LOAD, pkl, aot_dir],
                       capture_output=True, text=True, timeout=300,
                       env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr[-2000:]
    got = json.loads(r.stdout)
    assert got["hash"] == want
    assert got["counters"]["hits"] == 1 and got["counters"]["misses"] == 0

    # corrupt the published entry: a fresh cache must fall back to the
    # cold compile (same bytes), reject the entry, and heal it by
    # re-publishing — the cache can only ever cost a rejected read
    epath = os.path.join(aot_dir, entries[0])
    blob = bytearray(open(epath, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(epath, "wb") as fh:
        fh.write(bytes(blob))
    log2 = _CapLog()
    cache2 = AotCache(aot_dir, log=log2)
    out_fb = fetch(cache2.dispatcher(ladder)(batch))
    assert _out_hash(out_fb) == want
    assert [kw["reason"] for kw in log2["aot.reject"]] == ["corrupt"]
    assert cache2.stats()["misses"] == 1 and cache2.stats()["publishes"] == 1
    # healed: the re-published entry loads clean again
    digest = static_digest(ladder, "full", False, False)
    from daccord_tpu.runtime.supervisor import shape_key

    assert AotCache(aot_dir).load(shape_key(batch, ""), digest) is not None


# ---------------------------------------------------------------------------
# autoscaler: spawn / cooldown / capacity / drain / reap (deterministic)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def time(self):
        return self.t


class _FakeProc:
    _pid = 50000

    def __init__(self, cmd):
        _FakeProc._pid += 1
        self.pid = _FakeProc._pid
        self.cmd = cmd
        self.rc = None

    def poll(self):
        return self.rc

    def terminate(self):
        self.rc = -signal.SIGTERM

    def kill(self):
        self.rc = -signal.SIGKILL

    def wait(self, timeout=None):
        if self.rc is None:
            raise subprocess.TimeoutExpired("fake", timeout)
        return self.rc


def test_autoscaler_bursty_trace_spawn_drain_reap(tmp_path, monkeypatch):
    import daccord_tpu.serve.autoscale as asc
    from daccord_tpu.serve import AutoscaleConfig, Autoscaler

    procs = []

    class _FakeSub:
        TimeoutExpired = subprocess.TimeoutExpired
        STDOUT = subprocess.STDOUT

        @staticmethod
        def Popen(cmd, env=None, stdout=None, stderr=None):
            if stdout is not None:
                stdout.close()
            p = _FakeProc(cmd)
            procs.append((p, env))
            return p

    clock = _Clock(1000.0)
    monkeypatch.setattr(asc, "subprocess", _FakeSub)
    monkeypatch.setattr(asc, "time", clock)
    log = _CapLog()
    sc = Autoscaler(AutoscaleConfig(
        peer_dir=str(tmp_path / "fleet"), root=str(tmp_path / "autopeers"),
        max_peers=2, min_peers=1, spawn_burn=1.0, sustain_s=2.0,
        cooldown_s=5.0, idle_ttl_s=4.0, backend="native",
        slo_p99_s=0.25, spawn_env={"JAX_PLATFORMS": "cpu"}), log)

    hot = [_mk_peer("p0", burn=3.0)]
    sc.tick(hot)                                   # burst arrives
    assert sc.counters["spawns"] == 0              # spike != sustained
    clock.t = 1001.0
    sc.tick(hot)
    assert sc.counters["spawns"] == 0
    clock.t = 1002.5                               # sustained >= 2 s → spawn
    sc.tick(hot)
    assert sc.counters["spawns"] == 1
    cmd, env = procs[0][0].cmd, procs[0][1]
    assert "serve" in cmd and "--peer-dir" in cmd and "--slo-p99-s" in cmd
    assert env["JAX_PLATFORMS"] == "cpu"
    spawn_ev = log["scale.spawn"][0]
    assert spawn_ev["peer"] == "autopeer1" and spawn_ev["n_spawned"] == 1

    clock.t = 1003.0                               # still red: cooldown holds
    sc.tick(hot)
    assert sc.counters["spawns"] == 1
    clock.t = 1009.0      # cooled AND sustained — but live+pending hits the
    sc.tick(hot)          # cap: the spawn-storm guard
    assert sc.counters["spawns"] == 1

    # burn collapses; the spawned peer turns up ready and idle
    spawned = _mk_peer("autopeer1")
    quiet = [_mk_peer("p0", burn=0.0), spawned]
    clock.t = 1010.0
    sc.tick(quiet)                                 # idle clock starts
    assert sc.counters["drains"] == 0
    clock.t = 1012.0
    sc.tick([_mk_peer("p0"), _mk_peer("autopeer1", jobs_active=1)])
    clock.t = 1013.0                               # activity reset the clock
    sc.tick(quiet)
    clock.t = 1016.0
    sc.tick(quiet)
    assert sc.counters["drains"] == 0
    clock.t = 1017.5                               # idle >= 4 s → drain
    sc.tick(quiet)
    assert sc.counters["drains"] == 1
    assert log["scale.drain"][0] == {"peer": "autopeer1",
                                     "reason": "idle_ttl"}

    procs[0][0].rc = 0                             # the drained peer exits
    clock.t = 1018.0
    sc.tick([_mk_peer("p0")])
    assert sc.counters["reaps"] == 1
    reap = log["scale.reap"][0]
    assert reap["peer"] == "autopeer1" and reap["rc"] == 0
    assert sc.stats()["spawned"] == []

    # burn-band audit trail moved red → quiet exactly once each
    bands = [kw["band"] for kw in log["scale.burn"]]
    assert bands == [30, 0]
    sc.shutdown()


# ---------------------------------------------------------------------------
# live e2e: two in-process peers behind the router
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("router"))
    cfg = SimConfig(genome_len=1500, coverage=10, read_len_mean=500,
                    min_overlap=200, seed=5)
    return make_dataset(d, cfg, name="sv"), d


def _solo_bytes(out, d):
    import dataclasses

    from daccord_tpu.runtime.pipeline import correct_to_fasta
    from daccord_tpu.serve.jobs import JobSpec, build_job_config

    spec = JobSpec.from_json({"db": out["db"], "las": out["las"]}, d)
    cfg = build_job_config(spec, "native", True, 64, "fused", d, "solo")
    cfg = dataclasses.replace(cfg, native_solver=True, supervise=True,
                              events_path=None, ledger_path=None,
                              job_tag=None, quarantine_path=None)
    ref = os.path.join(d, "solo-native.fasta")
    if not os.path.exists(ref):
        correct_to_fasta(out["db"], out["las"], ref, cfg)
    with open(ref, "rb") as fh:
        return fh.read()


def _rreq(port, method, path, body=None, timeout=180):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return resp.status, resp.read()


@needs_native
def test_router_e2e_sticky_idempotent_spill(dataset, tmp_path):
    from daccord_tpu.serve import ConsensusService, ServeConfig
    from daccord_tpu.serve.http import start_server
    from daccord_tpu.serve.router import Router, RouterConfig, start_router

    out, d = dataset
    ref = _solo_bytes(out, d)
    peer_dir = str(tmp_path / "fleet")
    svcs, servers = {}, []
    for i in range(2):
        w = str(tmp_path / f"p{i}")
        svc = ConsensusService(ServeConfig(
            workdir=w, backend="native", backend_explicit=True, batch=64,
            workers=2, flush_lag_s=0.02, peer_dir=peer_dir))
        httpd, port, _ = start_server(svc, "127.0.0.1", 0)
        svc.announce(f"http://127.0.0.1:{port}")
        svcs[f"p{i}"] = svc
        servers.append((svc, httpd))
    rt = Router(RouterConfig(workdir=str(tmp_path / "router"),
                             peer_dir=peer_dir, poll_s=3600.0,
                             spill_burn=1.0))
    rhttpd, rport, _ = start_router(rt)
    try:
        rt.refresh()
        st, raw = _rreq(rport, "GET", "/v1/router")
        rs = json.loads(raw)
        assert rs["ready"] and len(rs["peers"]) == 2
        assert all(p["alive"] and p["ready"] for p in rs["peers"])

        # three same-tenant submits land on ONE peer (warmth stays put)
        jobs = []
        for i in range(3):
            st, raw = _rreq(rport, "POST", "/v1/jobs",
                            {"db": out["db"], "las": out["las"],
                             "tenant": "alice",
                             "idempotency_key": f"rt-e2e-{i}"})
            assert st == 201, raw
            jobs.append(json.loads(raw)["job"])
        owners = {rt.stats()["jobs"][j] for j in jobs}
        assert len(owners) == 1
        owner = owners.pop()

        # idempotent replay THROUGH the router: same key → same job, no
        # second admission
        st, raw = _rreq(rport, "POST", "/v1/jobs",
                        {"db": out["db"], "las": out["las"],
                         "tenant": "alice", "idempotency_key": "rt-e2e-0"})
        assert st == 200 and json.loads(raw)["job"] == jobs[0]
        assert json.loads(raw).get("idempotent") is True

        # proxied result + stream, byte-identical to the solo run
        st, body = _rreq(rport, "GET", f"/v1/jobs/{jobs[0]}/result?wait=1")
        assert st == 200 and body == ref
        st, sbody = _rreq(rport, "GET", f"/v1/jobs/{jobs[0]}/stream")
        assert st == 200 and sbody == ref
        for j in jobs[1:]:
            _rreq(rport, "GET", f"/v1/jobs/{j}/result?wait=1")

        # burn goes red on the owner → the next route spills off it
        svcs[owner]._slo_burn_last = 5.0
        rt.refresh()
        spilled = rt.route("alice")
        assert spilled.name != owner
        assert rt.counters["spills"] >= 1
        svcs[owner]._slo_burn_last = 0.0

        # unknown job: clean 404 from the router itself
        with pytest.raises(urllib.error.HTTPError) as ei:
            _rreq(rport, "GET", "/v1/jobs/j99999")
        assert ei.value.code == 404
    finally:
        rt.shutdown()
        rhttpd.shutdown()
        for svc, httpd in servers:
            svc.shutdown(drain=True)
            httpd.shutdown()
    _lint([os.path.join(str(tmp_path / "router"), "router.events.jsonl"),
           os.path.join(str(tmp_path / "p0"), "serve.events.jsonl"),
           os.path.join(str(tmp_path / "p1"), "serve.events.jsonl")])


# ---------------------------------------------------------------------------
# live e2e: SIGKILL mid-job, retry through the router lands exactly once
# ---------------------------------------------------------------------------

def _spawn_peer(workdir, root, tag, peer_dir, fault=None):
    ready = os.path.join(str(root), f"ready-{tag}.json")
    argv = [sys.executable, "-m", "daccord_tpu.tools.cli", "serve",
            "--workdir", str(workdir), "--backend", "native", "-b", "64",
            "--workers", "2", "--port", "0", "--ready-file", ready,
            "--checkpoint-reads", "4", "--flush-lag-ms", "20",
            "--peer-dir", str(peer_dir), "--lease-ttl-s", "600"]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__import__("daccord_tpu").__file__)))
    env["PYTHONPATH"] = pkg_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    if fault:
        env["DACCORD_FAULT"] = fault
    else:
        env.pop("DACCORD_FAULT", None)
    log = open(os.path.join(str(root), f"serve-{tag}.log"), "wb")
    proc = subprocess.Popen(argv, env=env, stdout=log, stderr=log)
    deadline = time.time() + 120
    port = None
    while time.time() < deadline:
        if os.path.exists(ready):
            try:
                port = json.load(open(ready))["port"]
                break
            except (OSError, json.JSONDecodeError, ValueError):
                pass
        if proc.poll() is not None:
            break
        time.sleep(0.05)
    return proc, port


def _journal(workdir):
    from daccord_tpu.serve.journal import replay

    return replay(os.path.join(str(workdir), "journal.jsonl"))


@needs_native
def test_kill_mid_proxy_retry_lands_exactly_once(dataset, tmp_path):
    """Two real peers behind the router; the job's owner SIGKILLs itself at
    the first progress append (running mid-batch, mid-proxy from the
    client's view). The client's retry with the SAME idempotency key rides
    the router to the survivor and lands exactly once, byte-identical."""
    from daccord_tpu.serve.router import Router, RouterConfig, start_router

    out, d = dataset
    ref = _solo_bytes(out, d)
    peer_dir = str(tmp_path / "fleet")
    os.makedirs(peer_dir, exist_ok=True)
    # pin the doomed peer: pick the tenant whose rendezvous owner is pA,
    # and give ONLY pA the deterministic SIGKILL (serve_crash:3 with a
    # 4-read checkpoint stride = the first progress append)
    tenant = next(t for t in (f"kt{i}" for i in range(1000))
                  if Router._score(t, "pA") > Router._score(t, "pB"))
    procA, portA = _spawn_peer(tmp_path / "pA", tmp_path, "a", peer_dir,
                               fault="serve_crash:3")
    procB, portB = _spawn_peer(tmp_path / "pB", tmp_path, "b", peer_dir)
    assert portA and portB
    rt = Router(RouterConfig(workdir=str(tmp_path / "router"),
                             peer_dir=peer_dir, poll_s=0.3,
                             lease_ttl_s=600.0))
    rhttpd, rport, _ = start_router(rt)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            rs = rt.stats()
            if sum(1 for p in rs["peers"]
                   if p["alive"] and p["ready"]) == 2:
                break
            time.sleep(0.1)
        else:
            pytest.fail(f"peers never turned ready: {rt.stats()['peers']}")

        body = {"db": out["db"], "las": out["las"], "tenant": tenant,
                "idempotency_key": "kill-once"}
        st, raw = _rreq(rport, "POST", "/v1/jobs", body)
        assert st == 201
        jid1 = json.loads(raw)["job"]
        assert rt.stats()["jobs"][jid1] == "pA"

        # the owner dies at its first progress append
        rc = procA.wait(timeout=180)
        assert rc == 137

        # retry the SAME key through the router until it lands; early
        # attempts may see 502 (dead proxy target) or 503 — both retryable
        jid2 = None
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                st, raw = _rreq(rport, "POST", "/v1/jobs", body, timeout=30)
                if st in (200, 201):
                    jid2 = json.loads(raw)["job"]
                    break
            except urllib.error.HTTPError as e:
                # dead proxy target (502) or a fleet mid-discovery (503):
                # both declare themselves retryable
                assert e.code in (502, 503), (e.code, e.read())
                assert json.loads(e.read()).get("retryable") is True
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.3)
        assert jid2 is not None, "retry never landed on the survivor"
        assert rt.stats()["jobs"][jid2] == "pB"

        st, got = _rreq(rport, "GET", f"/v1/jobs/{jid2}/result?wait=1")
        assert st == 200 and got == ref

        # exactly once: a further replay of the key dedupes onto the same
        # job — and the survivor's journal admitted the key ONCE
        st, raw = _rreq(rport, "POST", "/v1/jobs", body)
        assert st == 200 and json.loads(raw)["job"] == jid2
        entsB, _ = _journal(tmp_path / "pB")
        hitsB = [e for e in entsB.values() if e.idem == "kill-once"]
        assert len(hitsB) == 1 and hitsB[0].state == "committed"
        # fleet-wide: exactly one COMMITTED job ever carried the key (the
        # dead owner admitted it but never finished)
        entsA, _ = _journal(tmp_path / "pA")
        committed = [e for e in list(entsA.values()) + list(entsB.values())
                     if e.idem == "kill-once" and e.state == "committed"]
        assert len(committed) == 1

        # the router observed the death and said so
        evs = _events(rt)
        downs = [e for e in evs if e["event"] == "router.peer_down"]
        assert any(e["peer"] == "pA" for e in downs)
    finally:
        rt.shutdown()
        rhttpd.shutdown()
        for proc in (procA, procB):
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
    _lint([os.path.join(str(tmp_path / "router"), "router.events.jsonl"),
           os.path.join(str(tmp_path / "pB"), "serve.events.jsonl")])
