"""Two-stream tier ladder (ISSUE 4): byte parity with the fused ladder,
rescue-pool flush policy, per-stream stats, and supervisor replay.

Fast tier: the pool-membership rule, stats accounting, supervisor
two-stream replay against stub engines, and the CLI/schema surfaces — no
XLA ladder compiles. Slow tier: kernel-level and pipeline-level byte
parity (cfg2-style synthetic corpus), the DACCORD_FAULT matrix in split
mode, checkpoint/resume with a non-empty rescue pool, and the flush-lag
bound — split output must be byte-identical to fused EVERYWHERE.
"""

import json
import os

import numpy as np
import pytest

from daccord_tpu.kernels import KernelParams, TierLadder
from daccord_tpu.kernels.tensorize import BatchShape, WindowBatch, pad_batch
from daccord_tpu.kernels.tiers import rescue_candidates

# ---------------------------------------------------------------- fast tier


def _fake_ladder(n_tiers=2, wide=False, min_depth=3):
    params = [KernelParams(k=8, min_count=2 - (i > 0), wlen=40,
                           min_depth=min_depth)
              for i in range(n_tiers)]
    wide_p0 = None
    if wide:
        import dataclasses

        wide_p0 = dataclasses.replace(params[0], max_kmers=256)
    return TierLadder(params=params, tables={}, wide_p0=wide_p0)


def test_rescue_candidates_unit():
    out = dict(solved=np.asarray([True, False, False, True]),
               m_ovf=np.asarray([True, False, True, False]))
    nsegs = np.asarray([8, 8, 2, 8])

    # escalation only: unsolved-at-depth rows pool; shallow rows never do
    lad = _fake_ladder(n_tiers=2)
    np.testing.assert_array_equal(
        rescue_candidates(out, nsegs, lad), [False, True, False, False])

    # wide rescue adds solved-but-capped rows (row 0); shallow capped row 2
    # still excluded
    lad = _fake_ladder(n_tiers=2, wide=True)
    np.testing.assert_array_equal(
        rescue_candidates(out, nsegs, lad), [True, True, False, False])

    # single-tier ladder without wide rescue pools nothing (no rescue lane
    # exists in the fused program either)
    lad = _fake_ladder(n_tiers=1)
    np.testing.assert_array_equal(
        rescue_candidates(out, nsegs, lad), [False] * 4)


def test_rescue_density_stat():
    from daccord_tpu.runtime.pipeline import PipelineStats

    st = PipelineStats()
    assert st.rescue_density == 0.0
    st.n_rescue_windows, st.rescue_slots_executed = 120, 150
    assert st.rescue_density == pytest.approx(0.8)


def test_eventcheck_ladder_flush_schema(tmp_path):
    from daccord_tpu.tools.eventcheck import validate_events

    good = tmp_path / "flush.jsonl"
    good.write_text(json.dumps(
        {"t": 0.1, "ts": 1.0, "event": "ladder.flush", "rows": 100,
         "slots": 128, "reason": "lag", "bucket": 0}) + "\n")
    assert validate_events(str(good), strict=True) == []
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(
        {"t": 0.1, "event": "ladder.flush", "rows": "many"}) + "\n")
    errs = validate_events(str(bad))
    assert errs and any("slots" in e for e in errs)


def test_cli_ladder_flag_validation():
    from daccord_tpu.tools.cli import daccord_main

    with pytest.raises(SystemExit, match="ladder split"):
        daccord_main(["db", "las", "--ladder", "split", "--backend", "native"])


def test_kernelbench_rejects_unknown_stage():
    from daccord_tpu.tools.kernelbench import main as kb_main

    with pytest.raises(SystemExit, match="unknown stage"):
        kb_main(["--stages", "ladder_full,nope"])


def _mini_batch(stream="full", b=4, d=2, l=8):
    return WindowBatch(seqs=np.zeros((b, d, l), np.int8),
                       lens=np.zeros((b, d), np.int32),
                       nsegs=np.zeros(b, np.int32),
                       shape=BatchShape(depth=d, seg_len=l, wlen=l),
                       read_ids=np.zeros(b, np.int64),
                       wstarts=np.zeros(b, np.int64), stream=stream)


def test_pad_batch_preserves_stream():
    b = pad_batch(_mini_batch(stream="rescue"), 9)
    assert b.stream == "rescue" and b.size == 9


def test_supervisor_two_stream_replay(tmp_path, monkeypatch):
    """Failover with BOTH streams in flight: every in-flight handle —
    tier0 and rescue — replays on the fallback engine, and the stream-
    suffixed shape keys classify the two programs' cold compiles
    separately."""
    from daccord_tpu.runtime.faults import FaultPlan
    from daccord_tpu.runtime.supervisor import (DEGRADED, DeviceSupervisor,
                                                SupervisorConfig)
    from daccord_tpu.tools.eventcheck import validate_events
    from daccord_tpu.utils.obs import JsonlLogger

    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    dispatched = []

    def dispatch(batch):
        dispatched.append(batch.stream)
        return ("h", batch.stream)

    def fetch(h):
        return {"engine": "primary", "stream": h[1]}

    ev = str(tmp_path / "two_stream.events.jsonl")
    sup = DeviceSupervisor(
        dispatch, fetch, None,
        fallback_factory=lambda: (lambda b: {"engine": "fallback",
                                             "stream": b.stream}),
        log=JsonlLogger(ev),
        cfg=SupervisorConfig(backoff_base_s=0.01),
        faults=FaultPlan.parse("device_lost:3"), describe="stub")
    h_a = sup.dispatch(_mini_batch("tier0"))     # op 1 ok (Stream A)
    h_b = sup.dispatch(_mini_batch("rescue"))    # op 2 ok (Stream B)
    h_c = sup.dispatch(_mini_batch("tier0"))     # op 3: device lost
    assert sup.failed_over and sup.state == DEGRADED
    # all three in-flight batches replay on the fallback, streams intact
    assert sup.fetch(h_a) == {"engine": "fallback", "stream": "tier0"}
    assert sup.fetch(h_b) == {"engine": "fallback", "stream": "rescue"}
    assert sup.fetch(h_c) == {"engine": "fallback", "stream": "tier0"}
    recs = [json.loads(x) for x in open(ev)]
    # the tier0 program fingerprints with the :t0 suffix, the rescue batch
    # shares the full-ladder key — two distinct cold compiles, not three
    keys = [r["key"] for r in recs if r["event"] == "sup_compile"]
    assert sorted(keys) == ["B4xD2xL8", "B4xD2xL8:t0"]
    assert validate_events(ev, strict=True) == []


# ---------------------------------------------------------------- slow tier
# (XLA ladder compiles; byte parity is the acceptance bar)


@pytest.fixture(scope="module")
def cfg2ish(tmp_path_factory):
    """cfg2-style synthetic corpus, scaled to test wall: PacBio-like error
    profile at production-like depth (the regime where the top-M cap binds
    and tier-0 failures are the <10% tail, not a third of windows)."""
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path_factory.mktemp("split_e2e"))
    cfg = SimConfig(genome_len=4000, coverage=26, read_len_mean=800,
                    min_overlap=300, seed=23)
    return make_dataset(d, cfg, name="c2"), d


def _pipe_cfg(**kw):
    from daccord_tpu.runtime import PipelineConfig

    kw.setdefault("batch_size", 128)
    kw.setdefault("depth_buckets", ())
    return PipelineConfig(**kw)


@pytest.mark.slow
def test_split_ladder_kernel_parity(cfg2ish):
    """Kernel-level: solve_ladder_split == solve_ladder bitwise, including
    the wide overflow rescue (a tiny tier-0 cap makes it bind) and chunked
    Stream B batches (cross-batch compaction)."""
    from daccord_tpu.formats import LasFile, read_db
    from daccord_tpu.kernels import solve_ladder_split, tensorize_windows
    from daccord_tpu.kernels.tiers import fetch, solve_ladder, solve_tier0_async
    from daccord_tpu.oracle import cut_windows, refine_overlap
    from daccord_tpu.runtime.pipeline import estimate_profile_for_shard

    out, d = cfg2ish
    db = read_db(out["db"])
    las = LasFile(out["las"])
    cfg = _pipe_cfg()
    prof = estimate_profile_for_shard(db, las, cfg)
    shape = BatchShape(depth=32, seg_len=64, wlen=40)
    items = []
    for aread, pile in las.iter_piles():
        a = db.read_bases(aread)
        refined = [refine_overlap(o, a, db.read_bases(o.bread), las.tspace)
                   for o in pile]
        items.extend((aread, ws) for ws in
                     cut_windows(a, refined, w=40, adv=10))
        if len(items) >= 96:
            break
    batch = tensorize_windows(items[:96], shape)

    for lad_kw in (dict(),
                   dict(max_kmers=24, overflow_rescue=True)):
        ladder = TierLadder.from_config(prof, cfg.consensus, **lad_kw)
        ref = solve_ladder(batch, ladder)
        got = solve_ladder_split(batch, ladder, rescue_batch=32)
        for key in ("solved", "cons_len", "cons", "tier", "m_ovf"):
            np.testing.assert_array_equal(np.asarray(ref[key]),
                                          np.asarray(got[key]), key)
        if lad_kw:
            # the wide-rescue arm must actually have pooled something: the
            # tiny tier-0 cap must bind at the TIER0 stage (the final result
            # rightly carries no candidates — the M=256 rescue cleared them)
            out0 = fetch(solve_tier0_async(batch, ladder))
            assert rescue_candidates(out0, batch.nsegs, ladder).any()


@pytest.mark.slow
def test_split_vs_fused_pipeline_byte_parity_and_slots(cfg2ish):
    """ISSUE 4 acceptance: split output byte-identical to fused on the
    cfg2-style corpus; rescue_slots_executed drops >=5x at default config;
    non-final Stream B dispatches are >=0.8 dense."""
    from daccord_tpu.runtime import correct_to_fasta
    from daccord_tpu.tools.eventcheck import validate_events

    out, d = cfg2ish
    f_fused = os.path.join(d, "fused.fasta")
    f_split = os.path.join(d, "split.fasta")
    ev = os.path.join(d, "split.events.jsonl")
    s_fused = correct_to_fasta(out["db"], out["las"], f_fused, _pipe_cfg())
    s_split = correct_to_fasta(out["db"], out["las"], f_split,
                               _pipe_cfg(ladder_mode="split", events_path=ev))
    assert open(f_fused).read() == open(f_split).read()

    # both modes saw the same rescue demand; split paid >=5x fewer slots
    assert s_split.n_rescue_windows == s_fused.n_rescue_windows > 0
    assert s_fused.rescue_slots_executed >= 5 * s_split.rescue_slots_executed, (
        s_fused.rescue_slots_executed, s_split.rescue_slots_executed)
    assert s_split.n_dispatch_tier0 > 0 and s_split.n_dispatch_rescue > 0
    nonfinal = [di for di in s_split.rescue_dispatches
                if di["reason"] != "final"]
    for di in nonfinal:
        assert di["rows"] / di["slots"] >= 0.8, di

    # every Stream B dispatch left a lint-clean ladder.flush event
    assert validate_events(ev, strict=True) == []
    flushes = [json.loads(x) for x in open(ev)
               if '"ladder.flush"' in x]
    assert len(flushes) == s_split.n_dispatch_rescue


@pytest.mark.slow
def test_split_flush_lag_bound(cfg2ish):
    """Pool flush-lag bound: with a batch size the pool can never fill, a
    tight rescue_flush_reads forces 'lag' flushes (bounding emission lag);
    a loose one defers everything to the final drain."""
    from daccord_tpu.runtime import correct_to_fasta

    out, d = cfg2ish
    tight = correct_to_fasta(out["db"], out["las"],
                             os.path.join(d, "lag_tight.fasta"),
                             _pipe_cfg(ladder_mode="split",
                                       rescue_flush_reads=2))
    reasons = {di["reason"] for di in tight.rescue_dispatches}
    assert "lag" in reasons, tight.rescue_dispatches
    loose = correct_to_fasta(out["db"], out["las"],
                             os.path.join(d, "lag_loose.fasta"),
                             _pipe_cfg(ladder_mode="split",
                                       rescue_flush_reads=10 ** 6))
    # a deadline that can never expire leaves only capacity/final flushes
    assert {di["reason"] for di in loose.rescue_dispatches} <= {"full",
                                                               "final"}
    # flush policy changes batching only, never bytes
    assert (open(os.path.join(d, "lag_tight.fasta")).read()
            == open(os.path.join(d, "lag_loose.fasta")).read())


@pytest.mark.slow
def test_split_fault_matrix_byte_parity(cfg2ish, monkeypatch):
    """DACCORD_FAULT matrix in split mode: retries and mid-run failover
    (which replays BOTH streams on the degraded engine) must keep the FASTA
    byte-identical to the unfaulted fused run."""
    from daccord_tpu.runtime import correct_to_fasta

    out, d = cfg2ish
    ref = os.path.join(d, "matrix_ref.fasta")
    correct_to_fasta(out["db"], out["las"], ref, _pipe_cfg())
    ref_bytes = open(ref).read()
    monkeypatch.setenv("DACCORD_SUP_BACKOFF_S", "0.01")
    for fault, expect_degraded in (("dispatch_error:2", False),
                                   ("fetch_hang:2", False),
                                   ("device_lost:3", True)):
        monkeypatch.setenv("DACCORD_FAULT", fault)
        f = os.path.join(d, f"matrix_{fault.split(':')[0]}.fasta")
        st = correct_to_fasta(out["db"], out["las"], f,
                              _pipe_cfg(ladder_mode="split"))
        assert st.degraded == expect_degraded, fault
        assert open(f).read() == ref_bytes, fault
    monkeypatch.delenv("DACCORD_FAULT")


@pytest.mark.slow
def test_split_checkpoint_resume_with_pending_pool(cfg2ish, monkeypatch):
    """Mid-shard crash + resume while the rescue pool is non-empty: a huge
    rescue_flush_reads keeps windows pooled across many reads, the injected
    crash lands with rescue rows pending, and the resumed shard still
    produces the uninterrupted run's exact bytes (pooled windows simply
    re-solve after the checkpoint — in-order emission never published
    them)."""
    from daccord_tpu.parallel.launch import run_shard, shard_paths
    from daccord_tpu.runtime.faults import InjectedCrash

    out, d = cfg2ish
    # rescue_flush_reads holds pooled rows across a couple dozen reads, so
    # (deterministically, fixed seed) the injected crash lands while the
    # pool is non-empty — verified below from the crashed run's own batch
    # events (pool gauge), not assumed
    def cfg(log=None):
        return _pipe_cfg(batch_size=64, ladder_mode="split",
                         rescue_flush_reads=24, bucket_flush_reads=4,
                         log_path=log)

    ref_dir = os.path.join(d, "split_ref_out")
    m_ref = run_shard(out["db"], out["las"], ref_dir, 0, 1, cfg(),
                      checkpoint_every=2)
    assert not m_ref.get("degraded")
    ref_fasta = open(shard_paths(ref_dir, 0)["fasta"]).read()

    crash_dir = os.path.join(d, "split_crash_out")
    crash_log = os.path.join(d, "split_crash.log.jsonl")
    monkeypatch.setenv("DACCORD_FAULT", "crash:41")
    with pytest.raises(InjectedCrash):
        run_shard(out["db"], out["las"], crash_dir, 0, 1, cfg(crash_log),
                  checkpoint_every=2)
    paths = shard_paths(crash_dir, 0)
    assert os.path.exists(paths["progress"])   # crashed mid-shard, after ckpt
    assert not os.path.exists(paths["manifest"])
    batches = [json.loads(x) for x in open(crash_log)
               if '"event": "batch"' in x]
    assert batches and batches[-1]["pool"] > 0, \
        "crash must land with rescue rows pending for this test to bite"

    monkeypatch.delenv("DACCORD_FAULT")
    m = run_shard(out["db"], out["las"], crash_dir, 0, 1, cfg(),
                  checkpoint_every=2)
    assert m["resumed_at_read"] > 0
    assert open(paths["fasta"]).read() == ref_fasta
