"""Kernel parity harness: batched device solver vs the numpy oracle spec.

SURVEY.md §4 item 3: JAX window kernel vs oracle, window-by-window, exact
agreement expected when the kernel's caps (top-M, depth, seg-len) are not hit.
"""

import numpy as np
import pytest

from daccord_tpu.kernels import (
    BatchShape,
    KernelParams,
    TierLadder,
    solve_tiered,
    solve_window_batch,
    tensorize_windows,
)
from daccord_tpu.oracle import (
    ConsensusConfig,
    cut_windows,
    estimate_profile_two_pass,
    make_offset_likely,
    refine_overlap,
    solve_window,
)
from daccord_tpu.oracle.dbg import DBGParams, window_consensus
from daccord_tpu.sim import SimConfig, simulate

# XLA-compile-heavy e2e tier: excluded from `pytest -m 'not slow'` (fast tier)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def fixture():
    import jax.numpy as jnp

    cfg = SimConfig(genome_len=2500, coverage=16, read_len_mean=700, seed=21)
    res = simulate(cfg)
    aread = max(range(len(res.reads)), key=lambda i: len(res.reads[i].seq))
    pile = [o for o in res.overlaps if o.aread == aread]
    a = res.reads[aread].seq
    refined = [refine_overlap(o, a, res.reads[o.bread].seq, cfg.tspace) for o in pile]
    ccfg = ConsensusConfig()
    windows = cut_windows(a, refined, w=ccfg.w, adv=ccfg.adv)
    prof = estimate_profile_two_pass(refined, windows, ccfg, sample=12)
    ols = make_offset_likely(prof, ccfg)
    shape = BatchShape(depth=32, seg_len=64, wlen=40)
    batch = tensorize_windows([(aread, ws) for ws in windows], shape)
    return ccfg, windows, prof, ols, batch, shape


def test_kernel_oracle_parity_tier0(fixture):
    import jax.numpy as jnp

    ccfg, windows, prof, ols, batch, shape = fixture
    kp = KernelParams(k=8, min_count=2, edge_min_count=2, max_kmers=64, wlen=40)
    out = solve_window_batch(jnp.asarray(batch.seqs), jnp.asarray(batch.lens),
                             jnp.asarray(batch.nsegs), jnp.asarray(ols[8].table), kp)
    out = {k: np.asarray(v) for k, v in out.items()}
    p = DBGParams(k=8, min_count=2, edge_min_count=2)
    m_ovf = np.asarray(out["m_overflow"])
    agree = total = 0
    mismatches = []
    for i, ws in enumerate(windows):
        segs = [np.asarray(s[: shape.seg_len], dtype=np.int8) for s in ws.segments[: shape.depth]]
        r = window_consensus(segs, ols[8], p, wlen=40)
        ks = out["cons"][i][: out["cons_len"][i]] if out["solved"][i] else None
        total += 1
        if (r.seq is None) == (ks is None) and (r.seq is None or np.array_equal(r.seq, ks)):
            agree += 1
        else:
            mismatches.append(i)
    # every disagreement must be EXPLAINED: the kernel's top-M active-set cap
    # is the only divergence source vs the unbounded oracle, and the kernel
    # flags exactly the windows where the cap bound (m_overflow). Windows
    # with the full k-mer set must agree bit-for-bit.
    unexplained = [i for i in mismatches if not m_ovf[i]]
    assert not unexplained, (unexplained[:10], agree, total)
    assert agree / total >= 0.97, (agree, total, mismatches[:10])


def test_tier_ladder_solve_rate(fixture):
    ccfg, windows, prof, ols, batch, shape = fixture
    ladder = TierLadder.from_config(prof, ccfg)
    out = solve_tiered(batch, ladder, compact_size=32)
    rate = out["solved"].sum() / batch.size
    assert rate > 0.95, rate
    assert (out["tier"][out["solved"]] >= 0).all()
    # consensus lengths near the window size
    ls = out["cons_len"][out["solved"]]
    assert ls.min() >= 40 - 8 and ls.max() <= 40 + 8


def test_kernel_handles_empty_and_shallow_windows(fixture):
    import jax.numpy as jnp

    ccfg, windows, prof, ols, batch, shape = fixture
    kp = KernelParams(k=8, wlen=40)
    B, D, L = 4, shape.depth, shape.seg_len
    seqs = np.full((B, D, L), 4, dtype=np.int8)
    lens = np.zeros((B, D), dtype=np.int32)
    nsegs = np.zeros(B, dtype=np.int32)
    # window 1: a single segment (below min_depth)
    seqs[1, 0, :40] = np.resize(np.array([0, 1, 2, 3], np.int8), 40)
    lens[1, 0] = 40
    nsegs[1] = 1
    out = solve_window_batch(jnp.asarray(seqs), jnp.asarray(lens), jnp.asarray(nsegs),
                             jnp.asarray(ols[8].table), kp)
    assert not np.asarray(out["solved"]).any()


def test_edit_distance_formulations_agree():
    """Myers bit-parallel (hot path) == anti-diagonal == row-scan, including
    empty candidate/segment edges and lengths straddling the 32-bit word
    boundary."""
    import jax
    import jax.numpy as jnp

    from daccord_tpu.kernels.window_kernel import (
        _edit_distance_antidiag,
        _edit_distance_myers,
        _edit_distance_row_scan,
    )

    rng = np.random.default_rng(7)
    CN, SN = 48, 64
    cases = [(0, 17), (5, 0), (1, 1), (31, 40), (32, 40), (33, 64), (48, 64)]
    cases += [(int(rng.integers(0, CN + 1)), int(rng.integers(0, SN + 1)))
              for _ in range(40)]
    cands = np.full((len(cases), CN), 4, np.int8)
    segs = np.full((len(cases), SN), 4, np.int8)
    cls = np.zeros(len(cases), np.int32)
    sls = np.zeros(len(cases), np.int32)
    for i, (cl, sl) in enumerate(cases):
        cands[i, :cl] = rng.integers(0, 4, cl)
        segs[i, :sl] = rng.integers(0, 4, sl)
        cls[i], sls[i] = cl, sl
    f_my = jax.jit(jax.vmap(_edit_distance_myers))
    f_ad = jax.jit(jax.vmap(_edit_distance_antidiag))
    f_rs = jax.jit(jax.vmap(_edit_distance_row_scan))
    args = (jnp.asarray(cands), jnp.asarray(cls), jnp.asarray(segs), jnp.asarray(sls))
    d_my = np.asarray(f_my(*args))
    d_ad = np.asarray(f_ad(*args))
    d_rs = np.asarray(f_rs(*args))
    np.testing.assert_array_equal(d_my, d_ad)
    np.testing.assert_array_equal(d_my, d_rs)


def test_tensorize_caps_and_padding(fixture):
    ccfg, windows, prof, ols, batch, shape = fixture
    assert batch.seqs.shape == (batch.size, shape.depth, shape.seg_len)
    assert (batch.lens <= shape.seg_len).all()
    assert (batch.nsegs <= shape.depth).all()
    assert 0.0 < batch.pad_waste() < 1.0
    from daccord_tpu.kernels import pad_batch

    padded = pad_batch(batch, batch.size + 7)
    assert padded.size == batch.size + 7
    assert (padded.nsegs[-7:] == 0).all()


def test_packed_ladder_matches_dict_ladder(fixture):
    """The single-fetch packed result must decode bit-identically to the
    dict-of-arrays ladder output (pack_result/unpack_result round trip)."""
    import jax.numpy as jnp

    from daccord_tpu.kernels.tiers import (
        TierLadder, _ladder_jit, fetch, solve_ladder_async)

    ccfg, windows, prof, ols, batch, shape = fixture
    ladder = TierLadder.from_config(prof, ccfg)
    tables = tuple(ladder.tables[p.k] for p in ladder.params)
    ref = _ladder_jit(jnp.asarray(batch.seqs), jnp.asarray(batch.lens),
                      jnp.asarray(batch.nsegs), tables,
                      tuple(ladder.params), 256)
    ref = {k: np.asarray(v) for k, v in ref.items()}
    got = fetch(solve_ladder_async(batch, ladder, esc_cap=256))
    assert np.array_equal(got["cons"], ref["cons"])
    assert np.array_equal(got["cons_len"], ref["cons_len"])
    assert np.array_equal(got["solved"], ref["solved"])
    assert np.array_equal(got["tier"], ref["tier"])
    # err: inf-preserving f32 bitcast
    assert np.array_equal(np.isinf(got["err"]), np.isinf(ref["err"]))
    fin = ~np.isinf(ref["err"])
    assert np.array_equal(got["err"][fin], ref["err"][fin])
    assert got["esc_overflow"] == int(ref["esc_overflow"])


def test_packed_result_roundtrip_unit():
    """pack_result/unpack_result wire format: four int8 cons bytes per word, f32 err
    bitcast, tier+1 in 5 bits, per-window m_ovf at bit 5, esc_overflow in
    row 0's high bits — exact round trip."""
    import jax.numpy as jnp

    from daccord_tpu.kernels.tiers import pack_result, unpack_result

    rng = np.random.default_rng(3)
    B, CL = 7, 50
    cons = rng.integers(0, 5, (B, CL)).astype(np.int8)
    cons_len = rng.integers(0, CL + 1, B).astype(np.int32)
    err = rng.random(B).astype(np.float32)
    err[2] = np.inf
    tier = np.asarray([0, 1, 2, 3, -1, 0, 30], np.int32)   # 30 = max (tier+1 in 5 bits)
    m_ovf = np.asarray([1, 0, 1, 0, 1, 0, 1], bool)
    out = dict(cons=jnp.asarray(cons), cons_len=jnp.asarray(cons_len),
               err=jnp.asarray(err), tier=jnp.asarray(tier),
               m_ovf=jnp.asarray(m_ovf), esc_overflow=jnp.int32(12345))
    back = unpack_result(np.asarray(pack_result(out)), CL)
    np.testing.assert_array_equal(back["cons"], cons)
    np.testing.assert_array_equal(back["cons_len"], cons_len)
    np.testing.assert_array_equal(back["err"], err)
    np.testing.assert_array_equal(back["tier"], tier)
    np.testing.assert_array_equal(back["m_ovf"], m_ovf)
    np.testing.assert_array_equal(back["solved"], tier >= 0)
    assert back["esc_overflow"] == 12345


def test_overflow_rescue_parity(fixture):
    """Overflow rescue: device ladder == host-routed ladder bitwise, the
    rescue clears most top-M flags, and every still-flagged window is the
    only allowed oracle-divergence source (full-graph semantics restored)."""
    import jax.numpy as jnp

    from daccord_tpu.kernels.tiers import _ladder_jit, fetch

    ccfg, windows, prof, ols, batch, shape = fixture
    # tiny tier-0 cap so the cap binds on many windows and the rescue fires
    lad = TierLadder.from_config(prof, ccfg, max_kmers=24,
                                 rescue_max_kmers=256, overflow_rescue=True)
    assert lad.wide_p0 is not None and lad.wide_p0.max_kmers == 256
    tables = tuple(lad.tables[p.k] for p in lad.params)
    dev = fetch(_ladder_jit(jnp.asarray(batch.seqs), jnp.asarray(batch.lens),
                            jnp.asarray(batch.nsegs), tables,
                            tuple(lad.params), batch.size, False, False,
                            lad.wide_p0))
    host = solve_tiered(batch, lad, compact_size=32)
    for key in ("solved", "cons_len", "cons", "tier", "m_ovf"):
        np.testing.assert_array_equal(np.asarray(dev[key]), host[key], key)

    # vs the same cap without rescue: flags shrink, solve rate never drops
    base = solve_tiered(batch,
                        TierLadder.from_config(prof, ccfg, max_kmers=24),
                        compact_size=32)
    assert base["m_ovf"].sum() > 0, "cap must bind for this test to bite"
    assert host["m_ovf"].sum() < base["m_ovf"].sum()
    assert host["solved"].sum() >= base["solved"].sum()

    # rescued windows carry full-graph results: oracle agreement with the
    # M=256 flag as the only tolerated divergence, tier-0 windows only
    # (escalated windows solve at different k than the oracle's)
    p = DBGParams(k=8, min_count=2, edge_min_count=2)
    bad = []
    for i, ws in enumerate(windows):
        if host["tier"][i] != 0:
            continue
        segs = [np.asarray(s[: shape.seg_len], dtype=np.int8)
                for s in ws.segments[: shape.depth]]
        r = window_consensus(segs, ols[8], p, wlen=40)
        ks = host["cons"][i][: host["cons_len"][i]] if host["solved"][i] else None
        ok = (r.seq is None) == (ks is None) and (
            r.seq is None or np.array_equal(r.seq, ks))
        if not ok and not host["m_ovf"][i]:
            bad.append(i)
    assert not bad, bad[:10]
