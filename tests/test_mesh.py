"""Mesh-native solve path (ISSUE 12): the sharded ladder as a first-class
citizen of the supervisor/governor/paging/serve stack.

Runs on the 8 forced host CPU devices (conftest) — the off-pod recipe
``build_sharded_solver`` documents. The invariant behind every arm: sharding
(and re-sharding, after a partial-mesh shrink) a batch over devices cannot
change any window's bytes, because windows solve independently — so mesh-8
FASTA must be byte-identical to the single-device run under the whole fault
matrix. Heavy fleet/serve/crash-resume arms are in the slow tier; the core
parity + fault matrix stays in tier-1.
"""

import json
import os

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# cheap units (no XLA compile)
# ---------------------------------------------------------------------------


def _stub_ladder(depth=4):
    """Minimal TierLadder stand-in for solver-construction units."""
    from types import SimpleNamespace

    from daccord_tpu.kernels.window_kernel import KernelParams

    p = KernelParams(k=8, min_count=2, edge_min_count=2, wlen=40)
    return SimpleNamespace(params=[p], tables={p.k: None}, wide_p0=None)


def test_esc_cap_fixed_at_construction():
    """Satellite 1: esc_cap resolves once from the configured batch — a
    narrower (governor-bisected) batch reuses the same per-device cap
    instead of deriving a width-dependent one per dispatch."""
    from daccord_tpu.parallel.mesh import ShardedLadderSolver, make_mesh

    s = ShardedLadderSolver(_stub_ladder(), make_mesh(8), batch=512)
    assert s._esc_cap_for(512) == 64
    # narrower batches (bisect rungs) keep the SAME cap — no fresh program
    # per width beyond the unavoidable batch-dim recompile
    assert s._esc_cap_for(256) == 64
    assert s._esc_cap_for(64) == 64
    # wider-than-configured keeps overflow structurally impossible
    assert s._esc_cap_for(1024) == 128
    # explicit cap wins everywhere
    s2 = ShardedLadderSolver(_stub_ladder(), make_mesh(8), esc_cap=32,
                             batch=512)
    assert s2._esc_cap_for(512) == 32


def test_shrink_restore_and_cap_follow():
    from daccord_tpu.parallel.mesh import ShardedLadderSolver, make_mesh

    s = ShardedLadderSolver(_stub_ladder(), make_mesh(8), batch=512)
    assert s._esc_cap_for(512) == 64
    assert s.shrink() and s.nd == 4
    # the per-device slice doubled: the cap follows so overflow stays
    # structurally impossible on the shrunken mesh
    assert s._esc_cap_for(512) == 128
    assert s.shrink() and s.nd == 2
    assert s.shrink() and s.nd == 1
    assert not s.shrink()           # width 1: no smaller mesh exists
    s.restore()
    assert s.nd == 8 and s._esc_cap_for(512) == 64
    assert s.host_local             # forced host devices are cpu platform


def test_shape_key_mesh_suffix():
    """Mesh programs classify/fingerprint under :m<N> keys (composing with
    :t0), and the suffix follows the CURRENT mesh width after a shrink."""
    from daccord_tpu.kernels.tensorize import BatchShape, WindowBatch
    from daccord_tpu.parallel.mesh import ShardedLadderSolver, make_mesh
    from daccord_tpu.runtime.supervisor import DeviceSupervisor

    solver = ShardedLadderSolver(_stub_ladder(), make_mesh(8), batch=64)
    sup = DeviceSupervisor(lambda b: b, lambda h: h, inline=True,
                           fingerprint_prefix="cpu:", mesh=solver)
    b = WindowBatch(seqs=np.zeros((64, 4, 8), np.int8),
                    lens=np.zeros((64, 4), np.int32),
                    nsegs=np.zeros(64, np.int32), shape=BatchShape(4, 8, 40),
                    read_ids=np.zeros(64, np.int64),
                    wstarts=np.zeros(64, np.int64))
    assert sup._shape_key(b) == "cpu:B64xD4xL8:m8"
    import dataclasses

    assert sup._shape_key(dataclasses.replace(b, stream="tier0")) \
        == "cpu:B64xD4xL8:t0:m8"
    solver.shrink()
    assert sup._shape_key(b) == "cpu:B64xD4xL8:m4"
    # no mesh -> keys unchanged from the pre-mesh builds
    sup1 = DeviceSupervisor(lambda b: b, lambda h: h, inline=True,
                            fingerprint_prefix="cpu:")
    assert sup1._shape_key(b) == "cpu:B64xD4xL8"


def test_governor_quantum_widths():
    """Mesh-aware bisect: every rung width is a mesh multiple and the floor
    scales per device, so one device's ceiling shrinks every slice in
    lockstep instead of collapsing the batch to the scalar floor."""
    from daccord_tpu.kernels.tensorize import BatchShape, WindowBatch
    from daccord_tpu.runtime.governor import (CapacityError, CapacityGovernor,
                                              GovernorConfig)

    widths = []

    def solve(b):
        widths.append(b.size)
        if b.size > 16:
            raise CapacityError("RESOURCE_EXHAUSTED: too wide", width=b.size)
        return {"cons": np.zeros((b.size, 4), np.int8),
                "cons_len": np.zeros(b.size, np.int32),
                "err": np.zeros(b.size, np.float32),
                "solved": np.ones(b.size, bool),
                "tier": np.zeros(b.size, np.int32), "esc_overflow": 0}

    gov = CapacityGovernor(solve, cfg=GovernorConfig(min_width=1,
                                                     persist=False),
                           quantum_fn=lambda: 8)
    b = WindowBatch(seqs=np.zeros((128, 4, 8), np.int8),
                    lens=np.zeros((128, 4), np.int32),
                    nsegs=np.zeros(128, np.int32), shape=BatchShape(4, 8, 40),
                    read_ids=np.zeros(128, np.int64),
                    wstarts=np.zeros(128, np.int64))
    out = gov.solve(b, "cpu:B128xD4xL8:m8", reason="injected")
    assert len(out["solved"]) == 128
    assert all(w % 8 == 0 for w in widths), widths
    assert gov.ratchet["cpu:B128xD4xL8:m8"] == 16


def test_auto_batch_scales_by_mesh():
    from daccord_tpu.utils.obs import auto_batch_size

    assert auto_batch_size(False, "tpu") == 2048
    assert auto_batch_size(False, "tpu", mesh=8) == 16384
    assert auto_batch_size(False, "cpu", mesh=4) == 2048
    assert auto_batch_size(True) == 4096          # native ignores mesh


def test_fleet_worker_argv_forwards_mesh(tmp_path):
    """Satellite 6: the fleet forwards --mesh to daccord-shard workers and
    its capacity-requeue batch scales by mesh width."""
    from daccord_tpu.parallel.fleet import Fleet, FleetConfig

    cfg = FleetConfig(nshards=2, backend="cpu", mesh=8)
    f = Fleet("db", "las", str(tmp_path), cfg, faults=None)
    argv = f._worker_argv(0)
    i = argv.index("--mesh")
    assert argv[i + 1] == "8"
    assert f._worker_batch() == 512 * 8
    cfg1 = FleetConfig(nshards=2, backend="cpu")
    f1 = Fleet("db", "las", str(tmp_path), cfg1, faults=None)
    assert "--mesh" not in f1._worker_argv(0)


def test_solve_fingerprint_includes_mesh():
    from daccord_tpu.oracle.profile import ErrorProfile
    from daccord_tpu.runtime.pipeline import PipelineConfig
    from daccord_tpu.serve.jobs import solve_fingerprint

    prof = ErrorProfile(0.05, 0.05, 0.02)
    cfg = PipelineConfig()
    base = solve_fingerprint(prof, cfg, "cpu")
    assert solve_fingerprint(prof, cfg, "cpu", mesh=0) == base
    assert solve_fingerprint(prof, cfg, "cpu", mesh=8) != base
    assert solve_fingerprint(prof, cfg, "cpu", mesh=8) != \
        solve_fingerprint(prof, cfg, "cpu", mesh=4)


# ---------------------------------------------------------------------------
# e2e parity + fault matrix (tier-1: the acceptance criterion)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    from daccord_tpu.formats import LasFile, read_db
    from daccord_tpu.runtime import PipelineConfig, correct_shard
    from daccord_tpu.runtime.pipeline import estimate_profile_for_shard
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path_factory.mktemp("meshcorpus"))
    out = make_dataset(d, SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=700, min_overlap=300,
                                    seed=47), name="mesh")
    db = read_db(out["db"])
    las = LasFile(out["las"])
    base = dict(batch_size=64, depth_buckets=(16,))
    profile = estimate_profile_for_shard(db, las, PipelineConfig(**base))

    def run(**kw):
        cfg = PipelineConfig(**base, **kw)
        return [(rid, [f.tobytes() for f in frags])
                for rid, frags, _ in correct_shard(db, las, cfg,
                                                   profile=profile)]

    single = run()
    assert len(single) > 0
    return {"db": db, "las": las, "base": base, "profile": profile,
            "run": run, "single": single, "dir": d, "paths": out}


@pytest.fixture()
def throwaway_compcache(tmp_path, monkeypatch):
    # injected-fault ratchets/fingerprints must not land in the host's real
    # registry (same doctrine as the pounce governor smoke)
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))


def test_mesh_dense_parity(corpus):
    assert corpus["run"](mesh=8) == corpus["single"]


def test_mesh_paged_parity(corpus):
    """Paged + mesh compose: the page table shards, the pool replicates,
    and the FASTA stays byte-identical to the dense single-device run."""
    assert corpus["run"](mesh=8, paged="on") == corpus["single"]


def test_mesh_split_ladder_parity(corpus):
    """:t0 + :m<N> compose: Stream A runs mesh-wide tier0, rescue pools
    flush mesh-width Stream B batches — same bytes."""
    assert corpus["run"](mesh=8, ladder_mode="split") == corpus["single"]


def test_mesh_device_lost_partial_mesh_rung(corpus, tmp_path, monkeypatch,
                                            throwaway_compcache):
    """device_lost mid-mesh engages the partial-mesh degradation rung
    (8 -> 4), NOT whole-program failover, and the output is byte-identical."""
    monkeypatch.setenv("DACCORD_FAULT", "device_lost:2")
    ev = str(tmp_path / "lost.events.jsonl")
    from daccord_tpu.runtime import PipelineConfig, correct_shard

    cfg = PipelineConfig(**corpus["base"], mesh=8, events_path=ev)
    got = [(rid, [f.tobytes() for f in frags])
           for rid, frags, st in correct_shard(corpus["db"], corpus["las"],
                                               cfg, profile=corpus["profile"])]
    assert got == corpus["single"]
    evs = [json.loads(x) for x in open(ev)]
    kinds = [e["event"] for e in evs]
    assert "mesh.init" in kinds
    shr = [e for e in evs if e["event"] == "mesh.shrink"]
    assert shr and shr[0]["nd_from"] == 8 and shr[0]["nd_to"] == 4
    assert "sup_failover" not in kinds        # stayed on the (smaller) mesh
    done = [e for e in evs if e["event"] == "sup_done"][-1]
    assert done["mesh_shrinks"] >= 1 and not done["degraded"]
    # post-shrink dispatches classify under the :m4 key
    assert any(":m4" in e.get("key", "") for e in evs
               if e["event"] == "sup_compile")
    # lint the whole sidecar (mesh.* kinds are schema'd)
    from daccord_tpu.tools.eventcheck import validate_events

    assert validate_events(ev, strict=True) == []


def test_mesh_sdc_detect_attribute_parity(corpus, tmp_path, monkeypatch,
                                          throwaway_compcache):
    """``sdc:1@2`` silently corrupts member 2's result slice — no exception,
    valid alphabet, nothing downstream can notice by inspection. The shadow
    audit (rate 1.0 here: every row sampled, detection deterministic) must
    catch the byte divergence, attribute the culprit by per-member
    re-dispatch, strike the trust ratchet, and re-solve the poisoned batch
    on the reference so the FASTA stays byte-identical."""
    monkeypatch.setenv("DACCORD_FAULT", "sdc:1@2")
    # keep the ratchet below quarantine: this arm tests detect/attribute,
    # the shrink rung is the storm soak's job (BENCH_SDC)
    monkeypatch.setenv("DACCORD_TRUST_STRIKES", "99")
    ev = str(tmp_path / "sdc.events.jsonl")
    from daccord_tpu.runtime import PipelineConfig, correct_shard

    cfg = PipelineConfig(**corpus["base"], mesh=8, events_path=ev,
                         audit_rate=1.0)
    got = [(rid, [f.tobytes() for f in frags])
           for rid, frags, st in correct_shard(corpus["db"], corpus["las"],
                                               cfg, profile=corpus["profile"])]
    assert got == corpus["single"]            # the lie never reaches bytes
    evs = [json.loads(x) for x in open(ev)]
    sdc = [e for e in evs if e["event"] == "sup_sdc"]
    assert sdc and sdc[0]["divergent"] >= 1
    attrib = [e for e in evs if e["event"] == "audit.attrib"]
    assert attrib and {e["culprit"] for e in sdc + attrib} == {2}
    trust = [e for e in evs if e["event"] == "trust.state"]
    assert trust and trust[0]["device"] == 2 \
        and trust[0]["state_from"] == "TRUSTED" \
        and trust[0]["state_to"] == "SUSPECT"
    assert "mesh.shrink" not in [e["event"] for e in evs]  # no quarantine
    done = [e for e in evs if e["event"] == "sup_done"][-1]
    assert done["sdc_detected"] >= 1 and done["audits"] >= 1
    from daccord_tpu.tools.eventcheck import validate_events

    assert validate_events(ev, strict=True) == []


def test_mesh_device_oom_bisect_and_ratchet(corpus, tmp_path, monkeypatch,
                                            throwaway_compcache):
    """device_oom on a mesh dispatch walks the per-device bisect (widths
    stay mesh multiples) and ratchets under the :m8 key — persisted for the
    next run, byte-identical output, no failover."""
    monkeypatch.setenv("DACCORD_FAULT", "device_oom:2")
    monkeypatch.setenv("DACCORD_GOV_MIN_WIDTH", "2")
    ev = str(tmp_path / "oom.events.jsonl")
    from daccord_tpu.runtime import PipelineConfig, correct_shard

    cfg = PipelineConfig(**corpus["base"], mesh=8, events_path=ev)
    got = [(rid, [f.tobytes() for f in frags])
           for rid, frags, st in correct_shard(corpus["db"], corpus["las"],
                                               cfg, profile=corpus["profile"])]
    assert got == corpus["single"]
    evs = [json.loads(x) for x in open(ev)]
    assert not any(e["event"] == "sup_failover" for e in evs)
    shrinks = [e for e in evs if e["event"] == "governor.shrink"]
    assert shrinks and all(e["width_to"] % 8 == 0 for e in shrinks)
    rats = [e for e in evs if e["event"] == "governor.ratchet"]
    assert rats and ":m8" in rats[0]["key"]
    # ratchet persistence: the registry beside the (throwaway) compile cache
    # carries the :m8 key, so the NEXT run of this shape dispatches reduced
    from daccord_tpu.runtime.governor import load_ratchets

    persisted = load_ratchets()
    mesh_keys = [k for k in persisted if ":m8" in k]
    assert mesh_keys and persisted[mesh_keys[0]] == rats[-1]["width"]


# ---------------------------------------------------------------------------
# heavy arms: crash+resume, fleet worker, serve group (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_mesh_crash_resume_parity(corpus, tmp_path, monkeypatch,
                                  throwaway_compcache):
    """An injected hard crash mid-mesh-shard resumes from the checkpoint and
    the final FASTA is byte-identical to an uninterrupted single-device
    shard run."""
    from daccord_tpu.parallel import launch
    from daccord_tpu.runtime import PipelineConfig

    paths = corpus["paths"]
    ref_dir = str(tmp_path / "ref")
    cfg = PipelineConfig(**corpus["base"])
    launch.run_shard(paths["db"], paths["las"], ref_dir, 0, 1, cfg,
                     checkpoint_every=3)
    ref_fasta = open(launch.shard_paths(ref_dir, 0)["fasta"]).read()

    mesh_dir = str(tmp_path / "mesh")
    mcfg = PipelineConfig(**corpus["base"], mesh=8)
    # op 20 sits past the second grouped drain (max_inflight 8), so reads
    # have emitted and a checkpoint exists to resume from
    monkeypatch.setenv("DACCORD_FAULT", "crash:20")
    from daccord_tpu.runtime.faults import InjectedCrash

    with pytest.raises(InjectedCrash):
        launch.run_shard(paths["db"], paths["las"], mesh_dir, 0, 1, mcfg,
                         checkpoint_every=3)
    monkeypatch.delenv("DACCORD_FAULT")
    m = launch.run_shard(paths["db"], paths["las"], mesh_dir, 0, 1, mcfg,
                         checkpoint_every=3)
    assert m.get("resumed_at_read", 0) > 0
    assert open(launch.shard_paths(mesh_dir, 0)["fasta"]).read() == ref_fasta


@pytest.mark.slow
def test_fleet_worker_with_mesh(corpus, tmp_path):
    """A daccord-fleet run whose workers drive a local 8-device mesh merges
    byte-identically to a single-device fleet of the same shards."""
    from daccord_tpu.parallel.fleet import FleetConfig, run_fleet
    from daccord_tpu.parallel.launch import merge_shards

    paths = corpus["paths"]
    ref = str(tmp_path / "ref")
    cfg0 = FleetConfig(nshards=2, workers=2, backend="cpu", batch=64,
                       checkpoint_every=4, worker_telemetry=True)
    m0 = run_fleet(paths["db"], paths["las"], ref, cfg0, faults=None)
    assert not m0["poison"]
    mdir = str(tmp_path / "mesh")
    cfg8 = FleetConfig(nshards=2, workers=1, backend="cpu", batch=64,
                       checkpoint_every=4, mesh=8, worker_telemetry=True)
    m8 = run_fleet(paths["db"], paths["las"], mdir, cfg8, faults=None)
    assert not m8["poison"]
    f_ref = str(tmp_path / "ref.fasta")
    f_mesh = str(tmp_path / "mesh.fasta")
    merge_shards(ref, 2, f_ref)
    merge_shards(mdir, 2, f_mesh)
    assert open(f_mesh).read() == open(f_ref).read()
    # the worker really ran a mesh: its events sidecar carries mesh.init
    evs = [json.loads(x)
           for x in open(os.path.join(mdir, "shard0000.events.jsonl"))]
    assert any(e["event"] == "mesh.init" and e["nd"] == 8 for e in evs)


@pytest.mark.slow
def test_serve_mesh_group_mixed_batch_parity(corpus, tmp_path):
    """A serve mixed-job batch solved on a mesh-backed group: two jobs'
    rows merge into mesh-wide batches and each job's rows come back equal
    to its solo control (deterministic batcher-level arm)."""
    import dataclasses

    from daccord_tpu.kernels.tensorize import BatchShape, WindowBatch, \
        tensorize_windows
    from daccord_tpu.oracle import cut_windows, refine_overlap
    from daccord_tpu.runtime import PipelineConfig
    from daccord_tpu.serve.batcher import GroupConfig, SolveGroup

    db, las = corpus["db"], corpus["las"]
    cfg = PipelineConfig(**corpus["base"])
    # one real pile's windows as the job payload
    aread, pile = next(iter(las.iter_piles(None, None)))
    a = db.read_bases(aread)
    refined = [refine_overlap(o, a, db.read_bases(o.bread), las.tspace)
               for o in pile]
    windows = cut_windows(a, refined, w=cfg.consensus.w, adv=cfg.consensus.adv)
    shape = BatchShape(depth=cfg.depth, seg_len=cfg.seg_len,
                       wlen=cfg.consensus.w)
    wb = tensorize_windows([(aread, ws) for ws in windows], shape)
    n = (wb.size // 2) * 2
    half = n // 2
    rows_a = dataclasses.replace(
        wb, seqs=wb.seqs[:half], lens=wb.lens[:half], nsegs=wb.nsegs[:half],
        read_ids=wb.read_ids[:half], wstarts=wb.wstarts[:half])
    rows_b = dataclasses.replace(
        wb, seqs=wb.seqs[half:n], lens=wb.lens[half:n], nsegs=wb.nsegs[half:n],
        read_ids=wb.read_ids[half:n], wstarts=wb.wstarts[half:n])

    group = SolveGroup("k", corpus["profile"], cfg,
                       GroupConfig(backend="cpu", batch=n, mesh=8), name="g0")
    assert group.mesh_solver is not None and group.mesh_solver.nd == 8
    sa = group.job_solver("A")
    sb = group.job_solver("B")
    ha = sa.dispatch(rows_a)
    hb = sb.dispatch(rows_b)           # fills the pool -> ONE merged batch
    out_a = sa.fetch(ha)
    out_b = sb.fetch(hb)
    assert group.counters["mixed_batches"] >= 1
    # solo control: the same rows through a single-device solve
    from daccord_tpu.kernels.tiers import TierLadder, solve_tiered

    ladder = TierLadder.from_config(corpus["profile"], cfg.consensus,
                                    max_kmers=cfg.max_kmers,
                                    rescue_max_kmers=cfg.rescue_max_kmers)
    ref = solve_tiered(dataclasses.replace(
        wb, seqs=wb.seqs[:n], lens=wb.lens[:n], nsegs=wb.nsegs[:n],
        read_ids=wb.read_ids[:n], wstarts=wb.wstarts[:n]), ladder)
    np.testing.assert_array_equal(np.asarray(out_a["solved"]),
                                  ref["solved"][:half])
    np.testing.assert_array_equal(np.asarray(out_b["solved"]),
                                  ref["solved"][half:n])
    for i in range(half):
        np.testing.assert_array_equal(np.asarray(out_a["cons"][i]),
                                      ref["cons"][i])
        np.testing.assert_array_equal(np.asarray(out_b["cons"][i]),
                                      ref["cons"][half + i])


# ---------------------------------------------------------------------------
# dispatch pipeline (ISSUE 19): staged double-buffered dispatch
# ---------------------------------------------------------------------------


def _unit_batch(n):
    from daccord_tpu.kernels.tensorize import BatchShape, WindowBatch

    return WindowBatch(seqs=np.zeros((n, 4, 8), np.int8),
                       lens=np.ones((n, 4), np.int32),
                       nsegs=np.ones(n, np.int32), shape=BatchShape(4, 8, 40),
                       read_ids=np.arange(n, dtype=np.int64),
                       wstarts=np.zeros(n, np.int64))


def test_stage_launch_split_units(monkeypatch):
    """stage/launch decompose the dispatch: StagedBatch proxies the host
    batch, the sub-walls accrue, and a staged batch whose mesh changed since
    staging is discarded + re-staged at launch (the `restaged` counter)."""
    from daccord_tpu.parallel import mesh as meshmod

    s = meshmod.ShardedLadderSolver(_stub_ladder(), meshmod.make_mesh(8),
                                    batch=64)
    b = _unit_batch(60)                 # not a mesh multiple: pads to 64
    st = s.stage(b)
    assert isinstance(st, meshmod.StagedBatch)
    assert st.size == 60 and st.target == 64 and st.stream == "full"
    assert st.replay_batch is b         # the replayable truth is the HOST batch
    assert s.stage(st) is st            # idempotent on an already-staged batch
    dw = s.dispatch_walls()
    assert set(dw) == {"pack_s", "stage_s", "launch_s", "dispatch_s",
                       "restaged"}
    assert dw["stage_s"] > 0 and dw["restaged"] == 0
    assert dw["dispatch_s"] == dw["pack_s"] + dw["stage_s"] + dw["launch_s"]
    # shrink AFTER staging: the staged device buffers are stale — launch
    # must discard them and re-stage the host batch on the current mesh
    monkeypatch.setattr(meshmod, "_ladder_sharded_packed",
                        lambda *a, **k: "arr")
    assert s.shrink() and s.nd == 4
    _, B0 = s.launch(st)
    assert B0 == 60
    assert s.dispatch_walls()["restaged"] == 1
    # staging while a solve is outstanding counts as overlapped: health_map
    # reports the overlap_frac gauge in (0, 1]
    s.stage(_unit_batch(64))
    hm = s.health_map()
    ovr = [row["overlap_frac"] for row in hm["devices"].values()]
    assert all(o is not None and 0.0 < o <= 1.0 for o in ovr)


def test_supervisor_retains_host_batch_for_staged():
    """The supervisor unwraps a StagedBatch at dispatch: shape keys, the
    replay handle, and every fault path operate on the retained host batch
    (the staged device buffers are first-attempt-only)."""
    from daccord_tpu.parallel.mesh import ShardedLadderSolver, make_mesh
    from daccord_tpu.runtime.supervisor import DeviceSupervisor

    solver = ShardedLadderSolver(_stub_ladder(), make_mesh(8), batch=64)
    seen = []
    sup = DeviceSupervisor(lambda b: seen.append(type(b).__name__) or b,
                           lambda h: h, inline=True,
                           fingerprint_prefix="cpu:", mesh=solver)
    b = _unit_batch(64)
    st = solver.stage(b)
    h = sup.dispatch(st)
    assert seen == ["StagedBatch"]      # first attempt consumed the staged form
    assert h.batch is b                 # ...but the replay handle keeps the host batch
    assert h.key == "cpu:B64xD4xL8:m8"  # keyed off the host batch, not the pad


def test_mesh_pipeline_telemetry_and_optout_parity(corpus, tmp_path,
                                                   monkeypatch):
    """Tentpole: the default --mesh run double-buffers dispatch (stage under
    the in-flight solve) and emits the staged-dispatch telemetry; the
    DACCORD_MESH_PIPELINE=0 control arm takes the fused path — both
    byte-identical to the single-device run."""
    ev = str(tmp_path / "pipe.events.jsonl")
    from daccord_tpu.runtime import PipelineConfig, correct_shard

    cfg = PipelineConfig(**corpus["base"], mesh=8, events_path=ev)
    got = [(rid, [f.tobytes() for f in frags])
           for rid, frags, st in correct_shard(corpus["db"], corpus["las"],
                                               cfg, profile=corpus["profile"])]
    assert got == corpus["single"]
    evs = [json.loads(x) for x in open(ev)]
    kinds = [e["event"] for e in evs]
    pipe = [e for e in evs if e["event"] == "dispatch.pipeline"]
    assert pipe and pipe[0]["depth"] == 2
    stg = [e for e in evs if e["event"] == "dispatch.stage"]
    lch = [e for e in evs if e["event"] == "dispatch.launch"]
    assert stg and lch and len(stg) == len(lch)
    assert all(e["stage_s"] >= 0 and e["rows"] > 0 for e in stg)
    # the terminal record decomposes the dispatch wall into host-only
    # sub-walls that reconcile (daccord-prof --check enforces the same rule)
    done = [e for e in evs if e["event"] == "shard_done"][-1]
    sub = done["pack_s"] + done["stage_s"] + done["launch_s"]
    assert abs(sub - done["dispatch_s"]) <= max(0.05, 0.05 * done["dispatch_s"])
    assert done["restaged"] == 0        # no shrink in this arm
    from daccord_tpu.tools.eventcheck import validate_events

    assert validate_events(ev, strict=True) == []
    # opt-out control arm: fused dispatch, no pipeline telemetry, same bytes
    monkeypatch.setenv("DACCORD_MESH_PIPELINE", "0")
    ev0 = str(tmp_path / "nopipe.events.jsonl")
    cfg0 = PipelineConfig(**corpus["base"], mesh=8, events_path=ev0)
    got0 = [(rid, [f.tobytes() for f in frags])
            for rid, frags, st in correct_shard(corpus["db"], corpus["las"],
                                                cfg0,
                                                profile=corpus["profile"])]
    assert got0 == corpus["single"]
    kinds0 = [json.loads(x)["event"] for x in open(ev0)]
    assert "dispatch.pipeline" not in kinds0
    assert "dispatch.stage" not in kinds0


def test_pipelined_staged_replay_device_lost_attributed(corpus, tmp_path,
                                                        monkeypatch,
                                                        throwaway_compcache):
    """Staged-batch replay: device_lost:2@3 lands on a dispatch while the
    stager holds batch N+1. The staged device buffers are discarded, the
    mesh shrinks around member 3, and the retained HOST batch replays at
    :m4 — byte-identical, with the pipeline still on after the shrink."""
    monkeypatch.setenv("DACCORD_FAULT", "device_lost:2@3")
    ev = str(tmp_path / "staged_lost.events.jsonl")
    from daccord_tpu.runtime import PipelineConfig, correct_shard

    cfg = PipelineConfig(**corpus["base"], mesh=8, events_path=ev)
    got = [(rid, [f.tobytes() for f in frags])
           for rid, frags, st in correct_shard(corpus["db"], corpus["las"],
                                               cfg, profile=corpus["profile"])]
    assert got == corpus["single"]
    evs = [json.loads(x) for x in open(ev)]
    kinds = [e["event"] for e in evs]
    assert "dispatch.pipeline" in kinds
    shr = [e for e in evs if e["event"] == "mesh.shrink"]
    assert shr and shr[0]["nd_from"] == 8 and shr[0]["nd_to"] == 4
    assert "sup_failover" not in kinds
    # staged telemetry continued PAST the shrink (the pipeline survived it)
    last_shrink = max(i for i, e in enumerate(evs)
                      if e["event"] == "mesh.shrink")
    assert any(e["event"] == "dispatch.stage"
               for e in evs[last_shrink:])
    from daccord_tpu.tools.eventcheck import validate_events

    assert validate_events(ev, strict=True) == []


@pytest.mark.slow
def test_mesh_crash_resume_with_staged_batch(corpus, tmp_path, monkeypatch,
                                             throwaway_compcache):
    """A hard crash landing while the staging buffer is non-empty (crash:4
    — early enough that the stager is running ahead of the drain) must not
    lose bytes: the resume run replays from the checkpoint and the final
    FASTA matches the uninterrupted single-device shard."""
    from daccord_tpu.parallel import launch
    from daccord_tpu.runtime import PipelineConfig

    paths = corpus["paths"]
    ref_dir = str(tmp_path / "ref")
    cfg = PipelineConfig(**corpus["base"])
    launch.run_shard(paths["db"], paths["las"], ref_dir, 0, 1, cfg,
                     checkpoint_every=2)
    ref_fasta = open(launch.shard_paths(ref_dir, 0)["fasta"]).read()

    mesh_dir = str(tmp_path / "mesh")
    mcfg = PipelineConfig(**corpus["base"], mesh=8)
    monkeypatch.setenv("DACCORD_FAULT", "crash:4")
    from daccord_tpu.runtime.faults import InjectedCrash

    with pytest.raises(InjectedCrash):
        launch.run_shard(paths["db"], paths["las"], mesh_dir, 0, 1, mcfg,
                         checkpoint_every=2)
    monkeypatch.delenv("DACCORD_FAULT")
    launch.run_shard(paths["db"], paths["las"], mesh_dir, 0, 1, mcfg,
                     checkpoint_every=2)
    assert open(launch.shard_paths(mesh_dir, 0)["fasta"]).read() == ref_fasta
