"""CLI behaviors that must hold WITHOUT a backend probe (fast tier).

The r5 `--backend auto` fallback probes a possibly-dead tunnel for up to
150 s; usage errors must be checked before that probe or a typo'd command
stalls for minutes (code-review finding, r5). These tests run the real CLI
in a subprocess with a tight wall-clock budget.
"""

import subprocess
import sys
import time

import pytest


def _run(args, timeout=30):
    t0 = time.time()
    r = subprocess.run([sys.executable, "-m", "daccord_tpu.tools.cli", *args],
                       capture_output=True, text=True, timeout=timeout)
    return r, time.time() - t0


@pytest.mark.parametrize("args,needle", [
    (["daccord", "x.db", "x.las", "-o", "y.fa", "--block", "2", "-J", "0,4"],
     "mutually exclusive"),
    (["daccord", "x.db", "x.las", "-o", "y.fa", "-k", "3"],
     "supported range"),
    (["daccord", "x.db", "x.las", "-o", "y.fa", "--backend", "tpu",
      "-M", "0"], "requires --backend native"),
    (["daccord", "x.db", "x.las", "-o", "y.fa", "--backend", "native",
      "--mesh", "4"], "cannot be"),
])
def test_usage_errors_fast_with_auto_backend(args, needle):
    r, dt = _run(args)
    assert r.returncode != 0
    assert needle in r.stderr
    # well under any probe timeout: the check ran before backend resolution
    assert dt < 20
