"""Ingest integrity layer: validated decode, quarantine containment, durable
commit (ISSUE 2).

Fast tier: the scanner/taxonomy units run on tiny synthetic LAS/DB fixtures,
and the end-to-end corruption matrix drives the real pipeline with the native
C++ solver (no XLA ladder compiles), asserting the acceptance criteria —
quarantine-mode completion with byte-identical FASTA for every unaffected
read, strict-mode structured failure naming the byte offset, and
kill-between-fsync-points checkpoint resume with no lost or duplicated reads.
"""

import dataclasses
import json
import os
import shutil

import numpy as np
import pytest

from daccord_tpu.formats.dazzdb import read_db
from daccord_tpu.formats.ingest import (IngestError, IngestIssue,
                                        scan_las_range, sidecar_issues)
from daccord_tpu.formats.las import LasFile, index_las, write_las
from daccord_tpu.runtime import faults
from daccord_tpu.tools.eventcheck import validate_events


# ------------------------------------------------------------------ fixtures

@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path_factory.mktemp("ingest"))
    out = make_dataset(d, SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=7), name="t")
    return out, d


@pytest.fixture(scope="module")
def rlens(dataset):
    out, _ = dataset
    db = read_db(out["db"])
    return np.fromiter((r.rlen for r in db.reads), np.int64, db.nreads)


def _copy_las(dataset, tmp_path, name):
    out, _ = dataset
    p = str(tmp_path / name)
    shutil.copy(out["las"], p)
    return p


# ------------------------------------------------------- scanner / taxonomy

def test_scan_clean_file(dataset, rlens):
    out, _ = dataset
    las = LasFile(out["las"])
    rep = scan_las_range(las, rlens=rlens)
    assert rep.ok
    assert rep.n_records == las.novl
    assert rep.segments == [("clean", 16, os.path.getsize(out["las"]))]
    assert rep.n_piles == len(rep.pile_ranges) > 0


def test_scan_bad_coords_quarantines_one_pile(dataset, rlens, tmp_path):
    p = _copy_las(dataset, tmp_path, "bf.las")
    info = faults.corrupt_las_bitflip(p, 5)          # abpos MSB
    rep = scan_las_range(LasFile(p), rlens=rlens)
    assert [i.kind for i in rep.issues] == ["bad_coords"]
    assert rep.issues[0].offset == info["offset"] - faults.LAS_FIELD_OFF["abpos"]
    quar = [s for s in rep.segments if s[0] == "quarantine"]
    assert len(quar) == 1 and quar[0][1] == rep.issues[0].aread
    # every other pile stays clean
    ref = scan_las_range(LasFile(dataset[0]["las"]), rlens=rlens)
    assert rep.n_piles == ref.n_piles - 1


def test_scan_absurd_tlen_resyncs_to_next_pile(dataset, rlens, tmp_path):
    p = _copy_las(dataset, tmp_path, "tl.las")
    faults.corrupt_las_bitflip(p, 5, field="tlen", bit=30)
    ref = scan_las_range(LasFile(_copy_las(dataset, tmp_path, "clean.las")),
                         rlens=rlens)
    rep = scan_las_range(LasFile(p), rlens=rlens)
    assert rep.issues and rep.issues[0].kind in ("bad_tlen", "truncation")
    quar = [s for s in rep.segments if s[0] == "quarantine"]
    # framing loss contains exactly the corrupt pile; resync recovers the rest
    assert len(quar) == 1
    assert rep.n_piles == ref.n_piles - 1


def test_scan_negative_tlen_and_bread_oob(dataset, rlens, tmp_path):
    p = _copy_las(dataset, tmp_path, "neg.las")
    faults.corrupt_las_bitflip(p, 3, field="tlen", bit=31)   # sign bit
    rep = scan_las_range(LasFile(p), rlens=rlens)
    assert any(i.kind == "bad_tlen" and "negative" in i.detail
               for i in rep.issues)

    p2 = _copy_las(dataset, tmp_path, "br.las")
    faults.corrupt_las_bitflip(p2, 3, field="bread", bit=30)
    rep2 = scan_las_range(LasFile(p2), rlens=rlens)
    assert any(i.kind == "bad_read_id" and "bread" in i.detail
               for i in rep2.issues)


def test_scan_pile_boundary_corruption_blames_right_pile(dataset, rlens,
                                                         tmp_path):
    """A framing-intact corrupt record that OPENS a pile (trustworthy aread)
    must quarantine ITS pile — the preceding clean pile stays clean and the
    corrupt pile never half-corrects from a partial overlap set."""
    out, _ = dataset
    p = _copy_las(dataset, tmp_path, "pb.las")
    idx = index_las(out["las"], use_sidecar=False)
    data = open(out["las"], "rb").read()
    offs = faults._las_record_offsets(data)
    # first record of the SECOND pile (1-based record index)
    rec = offs.index(int(idx[1, 1])) + 1
    faults.corrupt_las_bitflip(p, rec)            # abpos MSB, framing intact
    ref = scan_las_range(LasFile(out["las"]), rlens=rlens)
    rep = scan_las_range(LasFile(p), rlens=rlens)
    quar = [s for s in rep.segments if s[0] == "quarantine"]
    assert [q[1] for q in quar] == [int(idx[1, 0])]   # pile 1, not pile 0
    assert rep.issues[0].aread == int(idx[1, 0])
    assert rep.n_piles == ref.n_piles - 1
    # pile 0 is still part of a clean segment
    assert any(s[0] == "clean" and s[1] <= int(idx[0, 1]) < s[2]
               for s in rep.segments)


def test_scan_boundary_aread_corruption_taints_both(dataset, rlens, tmp_path):
    """When the corrupt field IS the aread (membership ambiguous), both
    candidate piles are contained — over-quarantine beats silently
    correcting a possibly-incomplete pile."""
    out, _ = dataset
    p = _copy_las(dataset, tmp_path, "ta.las")
    idx = index_las(out["las"], use_sidecar=False)
    data = open(out["las"], "rb").read()
    offs = faults._las_record_offsets(data)
    rec = offs.index(int(idx[1, 1])) + 1
    faults.corrupt_las_bitflip(p, rec, field="aread", bit=30)
    ref = scan_las_range(LasFile(out["las"]), rlens=rlens)
    rep = scan_las_range(LasFile(p), rlens=rlens)
    quar = {q[1] for q in rep.segments if q[0] == "quarantine"}
    assert int(idx[0, 0]) in quar and int(idx[1, 0]) in quar
    assert rep.n_piles == ref.n_piles - 2


def test_scan_doubly_corrupt_record_terminates(dataset, rlens, tmp_path):
    """A record with BOTH a corrupt read id and a negative tlen must route
    through resync, never advance the walk by the garbage trace length."""
    p = _copy_las(dataset, tmp_path, "dbl.las")
    faults.corrupt_las_bitflip(p, 5, field="bread", bit=30)   # id first...
    faults.corrupt_las_bitflip(p, 5, field="tlen", bit=31)    # ...tlen too
    rep = scan_las_range(LasFile(p), rlens=rlens)
    assert rep.issues                       # detected, and the scan returned
    assert any(s[0] == "quarantine" for s in rep.segments)
    assert rep.n_piles > 0                  # resync recovered later piles


def test_scan_framing_loss_on_opening_record(dataset, rlens, tmp_path):
    """Framing loss on the very first record of the range: the record's
    (trusted) aread keys the quarantined pile, and resync must skip to the
    NEXT pile — never rejoin pile 0 mid-pile and correct it from partial
    evidence."""
    out, _ = dataset
    p = _copy_las(dataset, tmp_path, "open.las")
    idx = index_las(out["las"], use_sidecar=False)
    faults.corrupt_las_bitflip(p, 1, field="tlen", bit=30)
    rep = scan_las_range(LasFile(p), rlens=rlens)
    quar = [s for s in rep.segments if s[0] == "quarantine"]
    assert len(quar) == 1 and quar[0][1] == int(idx[0, 0])
    # no clean range may start inside pile 0's bytes
    pile1_off = int(idx[1, 1])
    assert all(s[1] >= pile1_off for s in rep.segments if s[0] == "clean")
    assert rep.pile_ranges and rep.pile_ranges[0][0] >= pile1_off


def test_scan_truncation_mid_file(dataset, rlens, tmp_path):
    p = _copy_las(dataset, tmp_path, "tr.las")
    las0 = LasFile(p)
    faults.corrupt_las_truncate(p, las0.novl - 3)
    rep = scan_las_range(LasFile(p), rlens=rlens)
    assert any(i.kind == "truncation" for i in rep.issues)
    assert rep.segments[-1][0] == "quarantine"


def test_scan_header_count_mismatch(dataset, rlens, tmp_path):
    # cut exactly at a record boundary: only the novl cross-check can see it
    p = _copy_las(dataset, tmp_path, "cut.las")
    data = open(p, "rb").read()
    offs = faults._las_record_offsets(data)
    open(p, "wb").write(data[: offs[-1]])
    rep = scan_las_range(LasFile(p), rlens=rlens)
    assert [i.kind for i in rep.issues] == ["truncation"]
    assert "promises" in rep.issues[0].detail


def test_ingest_error_report_names_offsets():
    err = IngestError([IngestIssue("bad_tlen", "x.las", 1234, "tlen=-7",
                                   aread=9)])
    s = str(err)
    assert "offset=1234" in s and "bad_tlen" in s and "aread=9" in s
    assert isinstance(err, ValueError)   # las-check's except clause contract
    assert err.kind == "bad_tlen" and err.offset == 1234


# -------------------------------------------------------------- las hardening

def test_lasfile_rejects_torn_header(tmp_path):
    p = str(tmp_path / "torn.las")
    open(p, "wb").write(b"\x01\x02\x03")
    with pytest.raises(IngestError) as ei:
        LasFile(p)
    assert ei.value.kind == "truncation"


def test_index_las_rejects_corrupt_tlen(dataset, tmp_path):
    """Satellite: a corrupt tlen must raise, never seek garbage and silently
    emit a wrong (short) index."""
    p = _copy_las(dataset, tmp_path, "idx.las")
    good = index_las(p, use_sidecar=False)
    faults.corrupt_las_bitflip(p, 5, field="tlen", bit=30)
    with pytest.raises(IngestError) as ei:
        index_las(p, use_sidecar=False)
    assert ei.value.kind == "bad_tlen"
    assert len(good) > 0


def test_iter_range_structured_errors(dataset, tmp_path):
    p = _copy_las(dataset, tmp_path, "it.las")
    faults.corrupt_las_bitflip(p, 5, field="tlen", bit=31)   # negative tlen
    with pytest.raises(IngestError) as ei:
        list(LasFile(p))
    assert ei.value.kind == "bad_tlen" and ei.value.offset > 0


def test_write_las_atomic_on_failure(dataset, tmp_path):
    """Satellite: a crash mid-write must never leave a valid-looking LAS
    (novl=0) at the target path; pre-existing content survives."""
    out, _ = dataset
    tspace, ovls = LasFile(out["las"]).tspace, list(LasFile(out["las"]))
    p = str(tmp_path / "w.las")
    write_las(p, tspace, ovls[:4])
    before = open(p, "rb").read()

    def exploding():
        yield ovls[0]
        raise RuntimeError("torn write")

    with pytest.raises(RuntimeError, match="torn write"):
        write_las(p, tspace, exploding())
    assert open(p, "rb").read() == before            # target untouched
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]  # tmp cleaned

    # fresh-path crash leaves NO file at all (downstream sees absent, not empty)
    p2 = str(tmp_path / "fresh.las")
    with pytest.raises(RuntimeError):
        write_las(p2, tspace, exploding())
    assert not os.path.exists(p2)


def test_torn_sidecar_rebuilds_and_is_reported(dataset, tmp_path):
    p = _copy_las(dataset, tmp_path, "sc.las")
    good = index_las(p)                              # builds sidecar
    sc = p + ".idx"
    open(sc, "wb").write(b"JUNKxxxxxxxx")
    os.utime(sc)                                     # keep it "fresh"
    issues = sidecar_issues(p)                       # las-check can see it
    assert issues and issues[0].kind == "bad_magic"
    again = index_las(p)                             # silent rebuild
    np.testing.assert_array_equal(good, again)
    assert sidecar_issues(p) == []                   # rebuilt sidecar healthy


# ---------------------------------------------------------------- dazzdb side

def test_read_db_validation(dataset, tmp_path):
    out, d = dataset
    dd = str(tmp_path / "dbv")
    shutil.copytree(d, dd)
    db_path = os.path.join(dd, "t.db")
    faults.corrupt_db_garbage(db_path, 3)
    with pytest.raises(IngestError) as ei:
        read_db(db_path)
    assert ei.value.kind == "db_read" and ei.value.offset >= 112
    db = read_db(db_path, strict=False)
    assert db.bad_reads == {2}
    # torn .idx header
    idx = os.path.join(dd, ".t.idx")
    open(idx, "wb").write(b"\x00" * 30)
    with pytest.raises(IngestError) as ei:
        read_db(db_path)
    assert ei.value.kind == "truncation"


# --------------------------------------------------- fault grammar extension

def test_data_fault_grammar():
    plan = faults.FaultPlan.parse("las_bitflip:4,db_garbage:2,fetch_hang:1")
    assert plan.has_data_faults()
    # data kinds never fire at device ops
    plan.op("dispatch")
    with pytest.raises(faults.FaultHang):
        plan.op("fetch")
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("las_bitflop:1")


def test_apply_data_faults_one_shot(dataset, tmp_path):
    p = _copy_las(dataset, tmp_path, "af.las")
    before = open(p, "rb").read()
    plan = faults.FaultPlan.parse("las_bitflip:4")
    fired = plan.apply_data_faults(las_path=p)
    assert [f["kind"] for f in fired] == ["las_bitflip"]
    assert open(p, "rb").read() != before
    assert plan.apply_data_faults(las_path=p) == []   # one-shot
    assert not plan.has_data_faults()


# ------------------------------------------------- e2e corruption matrix

@pytest.fixture(scope="module")
def native_ready():
    native = pytest.importorskip("daccord_tpu.native")
    if not native.available():
        pytest.skip("native library unavailable")
    return True


@pytest.fixture(scope="module")
def e2e(dataset, native_ready, tmp_path_factory):
    """Reference run + shared profile (explicit, so corrupt-run profile
    sampling cannot shift the comparison baseline)."""
    from daccord_tpu.runtime import PipelineConfig, correct_to_fasta
    from daccord_tpu.runtime.pipeline import estimate_profile_for_shard

    out, _ = dataset
    d = str(tmp_path_factory.mktemp("ingest_e2e"))
    db = read_db(out["db"])
    cfg = PipelineConfig(batch_size=64, native_solver=True)
    prof = estimate_profile_for_shard(db, LasFile(out["las"]), cfg)
    ref = os.path.join(d, "ref.fasta")
    s0 = correct_to_fasta(out["db"], out["las"], ref, cfg, profile=prof)
    assert s0.n_quarantined == 0 and s0.n_ingest_issues == 0
    return {"cfg": cfg, "prof": prof, "ref": ref, "d": d, "db": db}


def _read_fasta_map(path):
    from daccord_tpu.formats.fasta import read_fasta

    return {r.name: r.seq for r in read_fasta(path)}


def _pile_areads(las_path):
    return [int(a) for a, _ in index_las(las_path, use_sidecar=False)]


def _quarantine_run(e2e, las_path, name, db_path=None, dataset=None):
    from daccord_tpu.runtime import correct_to_fasta

    cfg = dataclasses.replace(e2e["cfg"], ingest_policy="quarantine",
                              events_path=os.path.join(e2e["d"],
                                                       f"{name}.ev.jsonl"))
    fasta = os.path.join(e2e["d"], f"{name}.fasta")
    stats = correct_to_fasta(db_path or dataset, las_path, fasta, cfg,
                             profile=e2e["prof"])
    assert validate_events(cfg.events_path) == []
    return fasta, stats, cfg


def _assert_contained(e2e, fasta, affected_areads, lost_areads=()):
    """Unaffected reads byte-identical to the reference; affected reads
    emitted uncorrected (raw bases); lost reads absent."""
    from daccord_tpu.utils.bases import ints_to_seq

    ref = _read_fasta_map(e2e["ref"])
    got = _read_fasta_map(fasta)
    aff = set(affected_areads) | set(lost_areads)
    for n2, seq in ref.items():
        rid = int(n2.removeprefix("read").split("/")[0])
        if rid in aff:
            continue
        assert got.get(n2) == seq, f"unaffected read changed: {n2}"
    for rid in affected_areads:
        raw = ints_to_seq(e2e["db"].read_bases(rid))
        assert got.get(f"read{rid}/0") == raw, f"read{rid} not emitted raw"
        assert f"read{rid}/1" not in got
    extra = {n2 for n2 in got if int(n2.removeprefix("read").split("/")[0]) in
             set(lost_areads)}
    assert not extra


def test_matrix_bitflip_coords(e2e, dataset, tmp_path):
    out, _ = dataset
    p = _copy_las(dataset, tmp_path, "m_bf.las")
    faults.corrupt_las_bitflip(p, 5)
    fasta, stats, cfg = _quarantine_run(e2e, p, "m_bf", db_path=out["db"])
    assert stats.n_quarantined == 1 and stats.n_ingest_issues == 1
    _assert_contained(e2e, fasta, affected_areads=[0])
    # sidecar records the containment (defaulted next to the output)
    side = [json.loads(x) for x in open(fasta + ".quarantine.jsonl")]
    assert side[0]["aread"] == 0 and side[0]["kind"] == "bad_coords"


def test_matrix_absurd_tlen(e2e, dataset, tmp_path):
    out, _ = dataset
    p = _copy_las(dataset, tmp_path, "m_tl.las")
    faults.corrupt_las_bitflip(p, 5, field="tlen", bit=30)
    fasta, stats, _ = _quarantine_run(e2e, p, "m_tl", db_path=out["db"])
    assert stats.n_quarantined == 1
    _assert_contained(e2e, fasta, affected_areads=[0])


def test_matrix_bread_out_of_bounds(e2e, dataset, tmp_path):
    out, _ = dataset
    p = _copy_las(dataset, tmp_path, "m_br.las")
    faults.corrupt_las_bitflip(p, 5, field="bread", bit=30)
    fasta, stats, _ = _quarantine_run(e2e, p, "m_br", db_path=out["db"])
    assert stats.n_quarantined == 1
    _assert_contained(e2e, fasta, affected_areads=[0])


def test_matrix_truncated_las(e2e, dataset, tmp_path):
    out, _ = dataset
    p = _copy_las(dataset, tmp_path, "m_tr.las")
    piles = _pile_areads(out["las"])
    # cut mid-way: the cut pile quarantines (emitted raw), later piles vanish
    las0 = LasFile(p)
    cut_rec = las0.novl * 2 // 3
    faults.corrupt_las_truncate(p, cut_rec)
    fasta, stats, _ = _quarantine_run(e2e, p, "m_tr", db_path=out["db"])
    assert stats.n_quarantined >= 1
    got = _read_fasta_map(fasta)
    ref = _read_fasta_map(e2e["ref"])
    got_rids = {int(n.removeprefix("read").split("/")[0]) for n in got}
    cut_at = min(r for r in got_rids
                 if f"read{r}/0" in got and got[f"read{r}/0"] != ref.get(f"read{r}/0"))
    affected = [r for r in got_rids if r >= cut_at]
    assert len(affected) <= 2     # cut pile (+ conservatively its neighbor)
    lost = [r for r in piles if r not in got_rids]
    _assert_contained(e2e, fasta, affected_areads=affected, lost_areads=lost)


def test_matrix_torn_idx_sidecar(e2e, dataset, tmp_path):
    """A torn .idx sidecar must cost a rescan, never correctness: output is
    byte-identical to the reference with nothing quarantined."""
    out, _ = dataset
    p = _copy_las(dataset, tmp_path, "m_sc.las")
    index_las(p)
    open(p + ".idx", "wb").write(b"LIDX\xff\xff\xff\xff short")
    os.utime(p + ".idx")
    fasta, stats, _ = _quarantine_run(e2e, p, "m_sc", db_path=out["db"])
    assert stats.n_quarantined == 0 and stats.n_ingest_issues == 0
    assert open(fasta).read() == open(e2e["ref"]).read()


def test_matrix_db_garbage(e2e, dataset, tmp_path):
    out, d = dataset
    dd = str(tmp_path / "m_db")
    shutil.copytree(d, dd)
    faults.corrupt_db_garbage(os.path.join(dd, "t.db"), 3)
    fasta, stats, _ = _quarantine_run(e2e, os.path.join(dd, "t.las"), "m_db",
                                      db_path=os.path.join(dd, "t.db"))
    # every pile referencing read 2 (as A or B) is contained
    assert stats.n_quarantined >= 1
    got = _read_fasta_map(fasta)
    ref = _read_fasta_map(e2e["ref"])
    assert "read2/0" not in got        # its bases are unrecoverable
    for n2, seq in got.items():
        assert ref.get(n2) == seq or n2.endswith("/0")


def test_matrix_strict_structured_failure(e2e, dataset, tmp_path):
    from daccord_tpu.runtime import correct_to_fasta

    out, _ = dataset
    p = _copy_las(dataset, tmp_path, "m_st.las")
    info = faults.corrupt_las_bitflip(p, 5)
    cfg = dataclasses.replace(e2e["cfg"], ingest_policy="strict")
    with pytest.raises(IngestError) as ei:
        correct_to_fasta(out["db"], p, os.path.join(e2e["d"], "st.fasta"),
                         cfg, profile=e2e["prof"])
    rec_off = info["offset"] - faults.LAS_FIELD_OFF["abpos"]
    assert f"offset={rec_off}" in str(ei.value)
    assert ei.value.offset == rec_off


def test_env_fault_injection_e2e(e2e, dataset, monkeypatch, tmp_path):
    """DACCORD_FAULT data kinds corrupt the artifacts at entry and the run
    contains them (the pounce corruption-fuzz path, in-process)."""
    out, _ = dataset
    p = _copy_las(dataset, tmp_path, "env.las")
    monkeypatch.setenv("DACCORD_FAULT", "las_bitflip:5")
    fasta, stats, cfg = _quarantine_run(e2e, p, "env", db_path=out["db"])
    assert stats.n_quarantined == 1
    evs = [json.loads(x)["event"] for x in open(cfg.events_path)]
    assert "ingest.fault" in evs and "ingest.quarantine" in evs
    _assert_contained(e2e, fasta, affected_areads=[0])


def test_cli_eprof_paths_honor_policy(e2e, dataset, tmp_path):
    """The -E pre-estimation pass must validate like the run itself: strict
    exits with the structured report (not a raw assertion from decoding a
    corrupt pile), quarantine estimates from clean piles and completes."""
    from daccord_tpu.tools.cli import daccord_main

    out, _ = dataset
    p = _copy_las(dataset, tmp_path, "ep.las")
    faults.corrupt_las_bitflip(p, 5)
    with pytest.raises(SystemExit, match="ingest integrity failure"):
        daccord_main([out["db"], p, "--backend", "native", "-b", "64",
                      "-E", str(tmp_path / "p.json"),
                      "-o", str(tmp_path / "s.fasta")])
    rc = daccord_main([out["db"], p, "--backend", "native", "-b", "64",
                       "--ingest-policy", "quarantine",
                       "-E", str(tmp_path / "p.json"),
                       "-o", str(tmp_path / "q.fasta")])
    assert rc == 0 and os.path.exists(tmp_path / "p.json")


# ---------------------------------------------- checkpoint / commit durability

def test_checkpoint_kill_between_fsync_points(dataset, native_ready,
                                              tmp_path, monkeypatch):
    """Kill after the FASTA fsync but before the manifest rename publishes:
    the stale manifest points at durable bytes only, so the resume truncates
    the orphan tail and finishes byte-identical — no lost, no duplicated
    reads."""
    from daccord_tpu.parallel.launch import run_shard, shard_paths
    from daccord_tpu.runtime import PipelineConfig
    from daccord_tpu.runtime.faults import InjectedCrash
    from daccord_tpu.utils import aio

    out, _ = dataset
    cfg = PipelineConfig(batch_size=32, native_solver=True,
                         depth_buckets=(), bucket_flush_reads=4)
    ref_dir = str(tmp_path / "ref")
    m_ref = run_shard(out["db"], out["las"], ref_dir, 0, 1, cfg,
                      checkpoint_every=2)
    ref_fasta = open(shard_paths(ref_dir, 0)["fasta"]).read()
    assert m_ref["reads"] >= 8

    crash_dir = str(tmp_path / "crash")
    real = aio.durable_replace
    state = {"commits": 0, "armed": True}

    def killing(tmp, dst):
        if state["armed"] and dst.endswith(".progress.json"):
            state["commits"] += 1
            if state["commits"] == 2:
                state["armed"] = False
                raise InjectedCrash("kill between fsync points")
        real(tmp, dst)

    monkeypatch.setattr(aio, "durable_replace", killing)
    with pytest.raises(InjectedCrash):
        run_shard(out["db"], out["las"], crash_dir, 0, 1, cfg,
                  checkpoint_every=2)
    paths = shard_paths(crash_dir, 0)
    prog = json.load(open(paths["progress"]))
    assert prog["emitted"] == 2          # checkpoint 2 never published
    # the FASTA holds checkpoint 2's (fsynced) bytes — longer than the
    # manifest's pointer, exactly the torn state the resume must truncate
    assert os.path.getsize(paths["fasta"]) > prog["fasta_bytes"]

    m = run_shard(out["db"], out["las"], crash_dir, 0, 1, cfg,
                  checkpoint_every=2)
    assert m["resumed_at_read"] == 2
    assert m["reads"] == m_ref["reads"]
    assert open(paths["fasta"]).read() == ref_fasta


def test_checkpointed_quarantine_run_over_corrupt_las(dataset, native_ready,
                                                      tmp_path):
    """A FRESH checkpointed shard run under quarantine completes on a
    framing-corrupt LAS (profile sampling must use the scan's clean piles,
    not index_las, which rightly rejects the file) — and a mid-shard RESUME
    over that file is refused with a structured SystemExit, never a silent
    duplicate read."""
    from daccord_tpu.parallel.launch import run_shard, shard_paths
    from daccord_tpu.runtime import PipelineConfig

    out, _ = dataset
    p = str(tmp_path / "ck.las")
    shutil.copy(out["las"], p)
    faults.corrupt_las_bitflip(p, 5, field="tlen", bit=30)
    cfg = PipelineConfig(batch_size=64, native_solver=True,
                         ingest_policy="quarantine")
    sdir = str(tmp_path / "s")
    m = run_shard(out["db"], p, sdir, 0, 1, cfg, checkpoint_every=3)
    assert m["quarantined"] == 1 and m["reads"] > 1

    # fabricate a mid-shard resume state over the same corrupt file
    paths = shard_paths(sdir, 0)
    os.remove(paths["manifest"])
    from daccord_tpu.formats.las import _HDR_SIZE
    json.dump({"emitted": 2, "fasta_bytes": 10,
               "counters": {"reads": 2, "windows": 0, "solved": 0,
                            "bases_out": 4, "wall_s": 0.1},
               "profile": [0.08, 0.04, 0.015],
               "byte_range": [_HDR_SIZE, os.path.getsize(p)]},
              open(paths["progress"], "wt"))
    with pytest.raises(SystemExit, match="cannot resume"):
        run_shard(out["db"], p, sdir, 0, 1, cfg, checkpoint_every=3)
    m2 = run_shard(out["db"], p, sdir, 0, 1, cfg, force=True,
                   checkpoint_every=3)
    assert m2["reads"] == m["reads"] and m2["quarantined"] == 1


def test_run_shard_torn_manifest_recomputes(dataset, native_ready, tmp_path):
    """Satellite: a torn shard manifest must not wedge the idempotent rerun."""
    from daccord_tpu.parallel.launch import run_shard, shard_paths
    from daccord_tpu.runtime import PipelineConfig

    out, _ = dataset
    sdir = str(tmp_path / "s")
    cfg = PipelineConfig(batch_size=64, native_solver=True)
    m0 = run_shard(out["db"], out["las"], sdir, 0, 1, cfg)
    paths = shard_paths(sdir, 0)
    open(paths["manifest"], "wt").write('{"shard": 0, "rea')   # torn JSON
    m1 = run_shard(out["db"], out["las"], sdir, 0, 1, cfg)
    assert m1["reads"] == m0["reads"]
    assert json.load(open(paths["manifest"]))["reads"] == m0["reads"]


def test_resume_after_torn_progress_manifest(dataset, native_ready, tmp_path):
    """Satellite: a torn progress manifest falls back to a fresh shard run
    (never splices onto an untrusted tail) and still matches the reference."""
    from daccord_tpu.parallel.launch import run_shard, shard_paths
    from daccord_tpu.runtime import PipelineConfig

    out, _ = dataset
    cfg = PipelineConfig(batch_size=64, native_solver=True)
    ref_dir = str(tmp_path / "ref")
    run_shard(out["db"], out["las"], ref_dir, 0, 1, cfg, checkpoint_every=3)
    ref_fasta = open(shard_paths(ref_dir, 0)["fasta"]).read()

    tdir = str(tmp_path / "torn")
    os.makedirs(tdir)
    paths = shard_paths(tdir, 0)
    open(paths["fasta"], "wt").write(">read9999/0\nACGT\n")   # untrusted tail
    open(paths["progress"], "wt").write('{"emitted": 3, "fasta_by')
    m = run_shard(out["db"], out["las"], tdir, 0, 1, cfg, checkpoint_every=3)
    assert "resumed_at_read" not in m
    assert open(paths["fasta"]).read() == ref_fasta


def test_pre_r4_checkpoint_rejection(dataset, native_ready, tmp_path):
    """Satellite: a pre-r4 checkpoint carrying retired --empirical-ol state
    must refuse to resume (SystemExit pointing at --force), not silently
    splice mixed-table output."""
    from daccord_tpu.formats.las import _HDR_SIZE
    from daccord_tpu.parallel.launch import run_shard, shard_paths
    from daccord_tpu.runtime import PipelineConfig

    out, _ = dataset
    sdir = str(tmp_path / "pre_r4")
    os.makedirs(sdir)
    paths = shard_paths(sdir, 0)
    open(paths["fasta"], "wt").write(">read0/0\nACGT\n")
    byte_range = [_HDR_SIZE, os.path.getsize(out["las"])]
    json.dump({"emitted": 2, "fasta_bytes": 5,
               "counters": {"reads": 2, "windows": 0, "solved": 0,
                            "bases_out": 4, "wall_s": 0.1},
               "profile": [0.08, 0.04, 0.015],
               "ol_counts": [[1, 2, 3]],
               "byte_range": byte_range},
              open(paths["progress"], "wt"))
    cfg = PipelineConfig(batch_size=64, native_solver=True)
    with pytest.raises(SystemExit, match="empirical-ol"):
        run_shard(out["db"], out["las"], sdir, 0, 1, cfg, checkpoint_every=2)
    # --force is the documented escape hatch: recompute from scratch
    m = run_shard(out["db"], out["las"], sdir, 0, 1, cfg, force=True,
                  checkpoint_every=2)
    assert m["reads"] > 0
