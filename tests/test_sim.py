"""Synthetic dataset generator invariants."""

import numpy as np

from daccord_tpu.formats import LasFile, read_db
from daccord_tpu.oracle import edit_distance
from daccord_tpu.sim import SimConfig, make_dataset, simulate
from daccord_tpu.utils import revcomp_ints

CFG = SimConfig(genome_len=3000, coverage=12, read_len_mean=800, seed=5)


def test_simulate_basic():
    res = simulate(CFG)
    assert len(res.reads) > 10
    assert len(res.overlaps) > 50
    # piles sorted by aread
    areads = [o.aread for o in res.overlaps]
    assert areads == sorted(areads)
    # both orientations appear
    assert any(o.is_comp for o in res.overlaps)
    assert any(not o.is_comp for o in res.overlaps)
    # symmetry: (a,b) implies (b,a)
    pairs = {(o.aread, o.bread) for o in res.overlaps}
    assert all((b, a) in pairs for a, b in pairs)


def test_trace_consistency():
    res = simulate(CFG)
    for o in res.overlaps[:100]:
        assert o.trace[:, 1].sum() == o.bepos - o.bbpos
        assert o.trace.shape[0] == o.ntiles(CFG.tspace)
        assert 0 <= o.abpos < o.aepos <= len(res.reads[o.aread].seq)
        blen = len(res.reads[o.bread].seq)
        assert 0 <= o.bbpos < o.bepos <= blen


def test_overlap_segments_align():
    """Tile segments must actually align: pair error rate < 3x single-read."""
    res = simulate(CFG)
    e = CFG.p_ins + CFG.p_del + CFG.p_sub
    checked = 0
    for o in res.overlaps[:20]:
        a = res.reads[o.aread].seq
        b = res.reads[o.bread].seq
        b_or = revcomp_ints(b) if o.is_comp else b
        bounds = o.tile_bounds(CFG.tspace)
        bpos = o.bbpos
        for t in range(len(bounds) - 1):
            atile = a[bounds[t] : bounds[t + 1]]
            btile = b_or[bpos : bpos + int(o.trace[t, 1])]
            bpos += int(o.trace[t, 1])
            d = edit_distance(atile, btile)
            assert d <= 3.0 * e * len(atile) + 8, (o.aread, o.bread, t, d, len(atile))
            checked += 1
    assert checked > 50


def test_make_dataset_roundtrip(tmp_path):
    out = make_dataset(str(tmp_path), CFG, name="t")
    db = read_db(out["db"])
    las = LasFile(out["las"])
    assert db.nreads == len(out["result"].reads)
    assert las.novl == len(out["result"].overlaps)
    tru = np.load(out["truth"])
    assert len(tru["genome"]) == CFG.genome_len
    assert len(tru["starts"]) == db.nreads
    # read bases round-trip through the DB
    np.testing.assert_array_equal(db.read_bases(0), out["result"].reads[0].seq)


def test_repeat_divergence():
    """Diverged repeat copies: the genome's two copies differ at ~divergence
    rate, and cross-copy induced overlaps carry those sites as extra trace
    diffs (they are what makes repeat piles damaging to correct)."""
    from daccord_tpu.sim.synth import _make_genome

    cfg = SimConfig(genome_len=8000, coverage=12, read_len_mean=900,
                    repeat_fraction=0.3, repeat_divergence=0.03, seed=41)
    rng = np.random.default_rng(cfg.seed)
    g, rep = _make_genome(cfg, rng)
    src, dst, rep_len, div_off = rep
    ndiff = int((g[src : src + rep_len] != g[dst : dst + rep_len]).sum())
    assert ndiff == len(div_off) == round(rep_len * 0.03)

    res = simulate(cfg)
    # exact-copy control: same layout, zero divergence
    res0 = simulate(SimConfig(**{**cfg.__dict__, "repeat_divergence": 0.0}))

    def mean_rate(result):
        # cross-copy overlaps are the clamped ones: both reads positioned on
        # different copies; identify via genome distance between the reads
        rates = []
        for o in result.overlaps:
            a, b = result.reads[o.aread], result.reads[o.bread]
            if abs(a.start - b.start) > rep_len:   # only cross-copy can overlap
                span = max(o.aepos - o.abpos, 1)
                rates.append(o.diffs / span)
        return np.mean(rates), len(rates)

    r_div, n_div = mean_rate(res)
    r0, n0 = mean_rate(res0)
    assert n_div > 10 and n0 > 10
    # diverged copies add ~3% pair error on cross-copy alignments (a little
    # less in practice: clamping and error-site collisions absorb some)
    assert r_div > r0 + 0.015, (r_div, r0)
