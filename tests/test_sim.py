"""Synthetic dataset generator invariants."""

import numpy as np

from daccord_tpu.formats import LasFile, read_db
from daccord_tpu.oracle import edit_distance
from daccord_tpu.sim import SimConfig, make_dataset, simulate
from daccord_tpu.utils import revcomp_ints

CFG = SimConfig(genome_len=3000, coverage=12, read_len_mean=800, seed=5)


def test_simulate_basic():
    res = simulate(CFG)
    assert len(res.reads) > 10
    assert len(res.overlaps) > 50
    # piles sorted by aread
    areads = [o.aread for o in res.overlaps]
    assert areads == sorted(areads)
    # both orientations appear
    assert any(o.is_comp for o in res.overlaps)
    assert any(not o.is_comp for o in res.overlaps)
    # symmetry: (a,b) implies (b,a)
    pairs = {(o.aread, o.bread) for o in res.overlaps}
    assert all((b, a) in pairs for a, b in pairs)


def test_trace_consistency():
    res = simulate(CFG)
    for o in res.overlaps[:100]:
        assert o.trace[:, 1].sum() == o.bepos - o.bbpos
        assert o.trace.shape[0] == o.ntiles(CFG.tspace)
        assert 0 <= o.abpos < o.aepos <= len(res.reads[o.aread].seq)
        blen = len(res.reads[o.bread].seq)
        assert 0 <= o.bbpos < o.bepos <= blen


def test_overlap_segments_align():
    """Tile segments must actually align: pair error rate < 3x single-read."""
    res = simulate(CFG)
    e = CFG.p_ins + CFG.p_del + CFG.p_sub
    checked = 0
    for o in res.overlaps[:20]:
        a = res.reads[o.aread].seq
        b = res.reads[o.bread].seq
        b_or = revcomp_ints(b) if o.is_comp else b
        bounds = o.tile_bounds(CFG.tspace)
        bpos = o.bbpos
        for t in range(len(bounds) - 1):
            atile = a[bounds[t] : bounds[t + 1]]
            btile = b_or[bpos : bpos + int(o.trace[t, 1])]
            bpos += int(o.trace[t, 1])
            d = edit_distance(atile, btile)
            assert d <= 3.0 * e * len(atile) + 8, (o.aread, o.bread, t, d, len(atile))
            checked += 1
    assert checked > 50


def test_make_dataset_roundtrip(tmp_path):
    out = make_dataset(str(tmp_path), CFG, name="t")
    db = read_db(out["db"])
    las = LasFile(out["las"])
    assert db.nreads == len(out["result"].reads)
    assert las.novl == len(out["result"].overlaps)
    tru = np.load(out["truth"])
    assert len(tru["genome"]) == CFG.genome_len
    assert len(tru["starts"]) == db.nreads
    # read bases round-trip through the DB
    np.testing.assert_array_equal(db.read_bases(0), out["result"].reads[0].seq)


def test_repeat_divergence():
    """Diverged repeat copies: the genome's two copies differ at ~divergence
    rate, and cross-copy induced overlaps carry those sites as extra trace
    diffs (they are what makes repeat piles damaging to correct)."""
    from daccord_tpu.sim.synth import _make_genome

    cfg = SimConfig(genome_len=8000, coverage=12, read_len_mean=900,
                    repeat_fraction=0.3, repeat_divergence=0.03, seed=41)
    rng = np.random.default_rng(cfg.seed)
    g, rep = _make_genome(cfg, rng)
    src, dst, rep_len, div_off = rep
    ndiff = int((g[src : src + rep_len] != g[dst : dst + rep_len]).sum())
    assert ndiff == len(div_off) == round(rep_len * 0.03)

    res = simulate(cfg)
    # exact-copy control: same layout, zero divergence
    res0 = simulate(SimConfig(**{**cfg.__dict__, "repeat_divergence": 0.0}))

    def mean_rate(result):
        # cross-copy overlaps are the clamped ones: both reads positioned on
        # different copies; identify via genome distance between the reads
        rates = []
        for o in result.overlaps:
            a, b = result.reads[o.aread], result.reads[o.bread]
            if abs(a.start - b.start) > rep_len:   # only cross-copy can overlap
                span = max(o.aepos - o.abpos, 1)
                rates.append(o.diffs / span)
        return np.mean(rates), len(rates)

    r_div, n_div = mean_rate(res)
    r0, n0 = mean_rate(res0)
    assert n_div > 10 and n0 > 10
    # diverged copies add ~3% pair error on cross-copy alignments (a little
    # less in practice: clamping and error-site collisions absorb some)
    assert r_div > r0 + 0.015, (r_div, r0)


def test_mismatch_knobs_off_stream_stable():
    """Knobs-off runs must reproduce the legacy rng stream exactly: cached
    fixtures and parity thresholds were generated with it."""
    a = simulate(CFG)
    b = simulate(SimConfig(**{**CFG.__dict__}))
    assert len(a.reads) == len(b.reads)
    for ra, rb in zip(a.reads, b.reads):
        np.testing.assert_array_equal(ra.seq, rb.seq)
    assert len(a.overlaps) == len(b.overlaps)


def test_homopolymer_indel_concentration():
    """With hp_indel_slope on, indels concentrate in homopolymer runs."""
    from daccord_tpu.sim.synth import _sample_noisy

    rng = np.random.default_rng(7)
    # genome rich in homopolymer runs
    g = np.repeat(rng.integers(0, 4, size=1500, dtype=np.int8),
                  rng.integers(1, 7, size=1500))

    change = np.nonzero(np.diff(g))[0] + 1
    bounds = np.concatenate([[0], change, [len(g)]])
    runlen = np.repeat(np.diff(bounds), np.diff(bounds))
    long_run = np.nonzero(runlen >= 4)[0]
    single = np.nonzero(runlen == 1)[0]

    def rate_ratio(cfg):
        r = np.random.default_rng(3)
        _, _, _, dels = _sample_noisy(g, 0, len(g), cfg, r,
                                      rmult=1.0 + 1e-12)  # force mismatch path
        r_long = np.isin(dels, long_run).sum() / len(long_run)
        r_single = max(np.isin(dels, single).sum() / len(single), 1e-9)
        return r_long / r_single

    assert rate_ratio(SimConfig(genome_len=100)) < 2.0
    assert rate_ratio(SimConfig(genome_len=100, hp_indel_slope=2.0)) > 3.0


def test_read_rate_dispersion():
    """read_rate_sigma spreads per-read error rates (fat right tail)."""
    cfg0 = SimConfig(genome_len=4000, coverage=15, read_len_mean=900, seed=9)
    cfgd = SimConfig(**{**cfg0.__dict__, "read_rate_sigma": 0.6})

    def per_read_rates(res):
        return np.array([r.err.sum() / max(len(r.seq), 1) for r in res.reads])

    r0 = per_read_rates(simulate(cfg0))
    rd = per_read_rates(simulate(cfgd))
    assert rd.std() > 2.0 * r0.std(), (r0.std(), rd.std())


def test_chimera_trace_accounting():
    """Chimeric reads keep the sim's core invariants: trace b-spans sum to
    the B interval and tile diffs reflect the foreign span's divergence."""
    cfg = SimConfig(genome_len=4000, coverage=18, read_len_mean=1200,
                    p_chimera=1.0, chimera_frac=0.25, seed=21)
    res = simulate(cfg)
    assert len(res.overlaps) > 20
    for o in res.overlaps[:80]:
        assert o.trace[:, 1].sum() == o.bepos - o.bbpos
        assert o.trace.shape[0] == o.ntiles(cfg.tspace)
    # every read long enough got a foreign insert: err runs of >= 50
    n_chim = 0
    for r in res.reads:
        if len(r.seq) > 600:
            d = np.diff(np.concatenate([[0], r.err.astype(np.int32), [0]]))
            runs = np.nonzero(d == -1)[0] - np.nonzero(d == 1)[0]
            if len(runs) and runs.max() >= 50:
                n_chim += 1
    assert n_chim >= max(1, sum(len(r.seq) > 600 for r in res.reads) // 2)


def test_coverage_dropout():
    """dropout_frac thins coverage inside the dropout region."""
    from daccord_tpu.sim.synth import _make_genome  # noqa: F401  (doc import)

    cfg = SimConfig(genome_len=20_000, coverage=20, read_len_mean=1500,
                    dropout_frac=0.2, dropout_factor=5.0, seed=33)
    res = simulate(cfg)
    # recover the dropout interval the same way simulate() drew it
    rng = np.random.default_rng(cfg.seed)
    _make_genome(cfg, rng)
    dlen = int(cfg.genome_len * cfg.dropout_frac)
    d0 = int(rng.integers(0, cfg.genome_len - dlen + 1))
    cov = np.zeros(cfg.genome_len)
    for r in res.reads:
        cov[r.start:r.end] += 1
    inside = cov[d0 + 200 : d0 + dlen - 200].mean()
    outside = np.concatenate([cov[: max(d0 - 200, 0)],
                              cov[d0 + dlen + 200 :]]).mean()
    assert inside < 0.55 * outside, (inside, outside)
