"""Multi-chip sharding tests on the 8-device virtual CPU mesh (SURVEY.md §4)."""

import os

import numpy as np
import pytest

# XLA-compile-heavy e2e tier: excluded from `pytest -m 'not slow'` (fast tier)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def ladder_and_batch():
    from daccord_tpu.kernels import BatchShape, TierLadder, tensorize_windows
    from daccord_tpu.oracle import (
        ConsensusConfig,
        cut_windows,
        estimate_profile_two_pass,
        refine_overlap,
    )
    from daccord_tpu.sim import SimConfig, simulate

    cfg = SimConfig(genome_len=2000, coverage=15, read_len_mean=650, seed=31)
    res = simulate(cfg)
    aread = max(range(len(res.reads)), key=lambda i: len(res.reads[i].seq))
    pile = [o for o in res.overlaps if o.aread == aread]
    a = res.reads[aread].seq
    refined = [refine_overlap(o, a, res.reads[o.bread].seq, cfg.tspace) for o in pile]
    ccfg = ConsensusConfig()
    windows = cut_windows(a, refined)
    prof = estimate_profile_two_pass(refined, windows, ccfg, sample=8)
    ladder = TierLadder.from_config(prof, ccfg)
    batch = tensorize_windows([(aread, ws) for ws in windows], BatchShape())
    return ladder, batch


def test_mesh_has_8_devices():
    import jax

    assert len(jax.devices()) == 8


def test_sharded_matches_single_device(ladder_and_batch):
    from daccord_tpu.kernels import solve_tiered
    from daccord_tpu.parallel import make_mesh, make_sharded_solver

    ladder, batch = ladder_and_batch
    mesh = make_mesh(8)
    solver = make_sharded_solver(ladder, mesh)
    out = solver(batch)
    ref = solve_tiered(batch, ladder)
    np.testing.assert_array_equal(out["solved"], ref["solved"])
    np.testing.assert_array_equal(out["cons_len"], ref["cons_len"])
    for i in range(batch.size):
        np.testing.assert_array_equal(out["cons"][i], ref["cons"][i])


def test_sharded_handles_nondivisible_batch(ladder_and_batch):
    from daccord_tpu.kernels.tensorize import WindowBatch
    from daccord_tpu.parallel import make_mesh, make_sharded_solver

    ladder, batch = ladder_and_batch
    # truncate to a size not divisible by 8
    n = batch.size - (batch.size % 8) - 3
    sub = WindowBatch(seqs=batch.seqs[:n], lens=batch.lens[:n], nsegs=batch.nsegs[:n],
                      shape=batch.shape, read_ids=batch.read_ids[:n],
                      wstarts=batch.wstarts[:n])
    solver = make_sharded_solver(ladder, make_mesh(8))
    out = solver(sub)
    assert out["solved"].shape == (n,)


def test_graft_entry_single_chip():
    import jax

    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert np.asarray(out["solved"]).all()


def test_graft_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_full_pipeline_through_mesh_solver(tmp_path):
    """The complete correction pipeline with the 8-device mesh solver produces
    byte-identical FASTA to the single-device path — long reads' windows shard
    freely across chips (the SP/long-context model, SURVEY.md §2.3)."""
    from daccord_tpu.formats import LasFile, read_db
    from daccord_tpu.kernels import TierLadder
    from daccord_tpu.parallel.mesh import make_mesh, make_sharded_solver
    from daccord_tpu.runtime import PipelineConfig, correct_shard
    from daccord_tpu.runtime.pipeline import estimate_profile_for_shard
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path)
    out = make_dataset(d, SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=700, min_overlap=300,
                                    seed=47), name="mesh")
    db = read_db(out["db"])
    las = LasFile(out["las"])
    # reads (~700bp) still span many windows and shard across all 8 devices;
    # two buckets keep the per-shape compile count down (parity is
    # scale-invariant — the small config tests the same property)
    cfg = PipelineConfig(batch_size=64, depth_buckets=(16,))
    profile = estimate_profile_for_shard(db, las, cfg)

    def run(solver):
        return [(rid, [f.tobytes() for f in frags])
                for rid, frags, _ in correct_shard(db, las, cfg, profile=profile,
                                                   solver=solver)]

    single = run(None)
    ladder = TierLadder.from_config(profile, cfg.consensus)
    mesh_out = run(make_sharded_solver(ladder, make_mesh(8)))
    assert len(single) > 0
    assert mesh_out == single


def test_multihost_shard_model(tmp_path):
    """Per-shard run + manifest + merge (the -J array-job model)."""
    from daccord_tpu.parallel import merge_shards, run_shard
    from daccord_tpu.runtime import PipelineConfig
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path)
    out = make_dataset(d, SimConfig(genome_len=1500, coverage=12, read_len_mean=500,
                                    min_overlap=250, seed=37), name="mh")
    outdir = str(tmp_path / "shards")
    m0 = run_shard(out["db"], out["las"], outdir, 0, 2, PipelineConfig(batch_size=128))
    m1 = run_shard(out["db"], out["las"], outdir, 1, 2, PipelineConfig(batch_size=128))
    assert m0["reads"] + m1["reads"] > 0
    # idempotence: rerun returns the manifest without recomputation
    m0b = run_shard(out["db"], out["las"], outdir, 0, 2)
    assert m0b == m0
    merged = str(tmp_path / "all.fasta")
    n = merge_shards(outdir, 2, merged)
    assert n == m0.get("fragments", 0) + m1.get("fragments", 0) or n >= 0


def test_checkpoint_resume_mid_shard(tmp_path, monkeypatch):
    """A crash between checkpoints resumes mid-shard and produces byte-identical
    FASTA vs an uninterrupted run (SURVEY.md §5 checkpoint row)."""
    import daccord_tpu.parallel.launch as launch
    from daccord_tpu.runtime import PipelineConfig
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path)
    out = make_dataset(d, SimConfig(genome_len=1500, coverage=12, read_len_mean=500,
                                    min_overlap=250, seed=11), name="ck")
    cfg = PipelineConfig(batch_size=64)

    # reference: uninterrupted run
    ref_dir = str(tmp_path / "ref")
    m_ref = launch.run_shard(out["db"], out["las"], ref_dir, 0, 1, cfg,
                             checkpoint_every=3)
    assert m_ref["reads"] >= 8, m_ref
    ref_fasta = open(launch.shard_paths(ref_dir, 0)["fasta"]).read()

    # crashing run: die after 5 emitted reads (checkpoint every 2 -> progress
    # records 4, the 5th read's partial FASTA tail must be truncated on resume)
    crash_dir = str(tmp_path / "crash")
    real = launch.correct_shard

    def crashing(db, las, c, start=None, end=None, **kw):
        for i, item in enumerate(real(db, las, c, start, end, **kw)):
            if i == 5:
                raise RuntimeError("injected crash")
            yield item

    monkeypatch.setattr(launch, "correct_shard", crashing)
    with pytest.raises(RuntimeError, match="injected crash"):
        launch.run_shard(out["db"], out["las"], crash_dir, 0, 1, cfg,
                         checkpoint_every=2)
    prog_path = launch.shard_paths(crash_dir, 0)["progress"]
    import json as _json
    prog = _json.load(open(prog_path))
    assert prog["emitted"] == 4
    monkeypatch.setattr(launch, "correct_shard", real)

    m_res = launch.run_shard(out["db"], out["las"], crash_dir, 0, 1, cfg,
                             checkpoint_every=2)
    assert m_res["resumed_at_read"] == 4
    assert m_res["reads"] == m_ref["reads"]
    res_fasta = open(launch.shard_paths(crash_dir, 0)["fasta"]).read()
    assert res_fasta == ref_fasta
    assert not os.path.exists(prog_path)


def test_two_process_jax_distributed(tmp_path):
    """Real multi-host: two OS processes form a jax.distributed group (CPU
    backend), each corrects its own LAS byte-range shard (the zero-traffic
    data plane), and the merged FASTA is byte-identical to a single-process
    run of the same two shards."""
    import socket
    import subprocess
    import sys

    from daccord_tpu.parallel.launch import merge_shards, run_shard
    from daccord_tpu.runtime.pipeline import PipelineConfig
    from daccord_tpu.sim import SimConfig, make_dataset

    out = make_dataset(str(tmp_path / "data"),
                       SimConfig(genome_len=1500, coverage=12, read_len_mean=500,
                                 min_overlap=200, seed=41), name="mh")

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = f"""
import jax, sys
jax.config.update("jax_platforms", "cpu")
from daccord_tpu.parallel.launch import init_distributed, run_shard
from daccord_tpu.runtime.pipeline import PipelineConfig

pid, np_ = init_distributed("127.0.0.1:{port}", num_processes=2,
                            process_id=int(sys.argv[1]))
assert np_ == 2, np_
m = run_shard({out['db']!r}, {out['las']!r}, sys.argv[2], pid, 2,
              PipelineConfig(batch_size=128))
print("proc", pid, "reads", m["reads"])
"""
    d_dist = str(tmp_path / "dist")
    procs = [subprocess.Popen([sys.executable, "-c", worker, str(i), d_dist],
                              stdout=subprocess.PIPE, stderr=subprocess.PIPE)
             for i in range(2)]
    try:
        for p in procs:
            so, se = p.communicate(timeout=600)
            assert p.returncode == 0, (so.decode()[-2000:], se.decode()[-2000:])
    finally:
        for p in procs:  # never leak an orphan worker on failure/timeout
            if p.poll() is None:
                p.kill()
                p.communicate()

    d_ref = str(tmp_path / "ref")
    for i in range(2):
        run_shard(out["db"], out["las"], d_ref, i, 2, PipelineConfig(batch_size=128))
    f_dist = str(tmp_path / "dist.fasta")
    f_ref = str(tmp_path / "ref.fasta")
    merge_shards(d_dist, 2, f_dist)
    merge_shards(d_ref, 2, f_ref)
    assert open(f_dist).read() == open(f_ref).read()
