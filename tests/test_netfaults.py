"""Network fault matrix units (ISSUE 18).

The injectable socket fault kinds (``runtime/faults.py`` ``net_*``), the
``serve/netio`` choke point every router/autoscaler/client HTTP call rides
(per-domain deadlines, transient-only bounded retries, body/trailer
integrity, per-peer circuit breaker, hedged reads), and the failure
asymmetries the fleet owes a flaky wire: a hung healthz costs one bounded
deadline (the poll loop keeps ticking), a fresh-leased unreachable peer is
PARTITIONED — routed around, never drained/reaped — and a client that
hangs up mid-proxied-stream is classified ``router.client_gone``, never
blamed on the peer. The end-to-end storm lives in ``bench.run_net_soak``
(slow rung here, pounce smoke + ``DACCORD_BENCH_NET=1`` elsewhere).
"""

import errno
import json
import os
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from daccord_tpu.runtime.faults import FaultPlan
from daccord_tpu.serve import netio


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test leaves the process-wide netio fault hook as it found it
    (the plan and its counters are process-global by design)."""
    yield
    netio.install_faults(None)


class _CapLog:
    """Capture logger matching the obs logger surface."""

    def __init__(self):
        self.events = []

    def log(self, event, **kw):
        self.events.append((event, kw))

    def __getitem__(self, name):
        return [kw for ev, kw in self.events if ev == name]

    def close(self):
        pass


def _lint(paths):
    from daccord_tpu.tools.eventcheck import validate_events

    for p in paths:
        errs = validate_events(p, strict=True)
        assert not errs, (p, errs[:5])


# ---------------------------------------------------------------------------
# grammar + counters
# ---------------------------------------------------------------------------

def test_net_fault_grammar_parse():
    p = FaultPlan.parse("net_refused:1@healthz,net_reset:2,net_hang:1@stream"
                        ",net_torn:500@result,net_slow:150@stream")
    kinds = {(s.kind, s.at, s.domain) for s in p.specs}
    assert ("net_refused", 1, "healthz") in kinds
    assert ("net_reset", 2, "") in kinds
    assert ("net_hang", 1, "stream") in kinds
    assert ("net_torn", 500, "result") in kinds
    assert ("net_slow", 150, "stream") in kinds
    assert p.has_net_faults()
    with pytest.raises(ValueError):
        FaultPlan.parse("net_reset:1@attic")       # unknown net domain
    with pytest.raises(ValueError):
        FaultPlan.parse("serve_crash:1@submit")    # @domain net_*/io_* only
    with pytest.raises(ValueError):
        FaultPlan.parse("net_bogus:1")


def test_net_check_domain_scoped_counter():
    """An ``@submit`` spec indexes ONLY submit-class attempts: healthz
    polls interleaving never advance it toward firing."""
    p = FaultPlan.parse("net_reset:2@submit")
    assert p.net_check("healthz") is None
    assert p.net_check("healthz") is None
    assert p.net_check("submit") is None           # submit attempt #1
    s = p.net_check("submit")                      # #2: fires
    assert s is not None and s.kind == "net_reset"
    assert p.net_check("submit") is None           # one-shot
    assert not p.has_net_faults()


def test_net_torn_first_op_and_slow_continuous():
    """``net_torn:N`` carries a BYTE offset, so it fires on the first
    matching attempt; ``net_slow:MS`` is a duration — continuous, never
    fired out (the grey-slow peer stays slow all run)."""
    p = FaultPlan.parse("net_torn:500@stream,net_slow:25@stream")
    assert p.net_slow_ms("stream") == 25.0
    assert p.net_slow_ms("submit") == 0.0
    assert p.net_check("submit") is None
    s = p.net_check("stream")
    assert s is not None and s.kind == "net_torn" and s.at == 500
    assert p.net_check("stream") is None
    assert p.has_net_faults()                      # net_slow still applies
    # undomained slow applies to every RPC class
    assert FaultPlan.parse("net_slow:10").net_slow_ms("healthz") == 10.0


def test_env_fault_plan_reaches_netio(monkeypatch):
    """DACCORD_FAULT resolves lazily inside netio (the aio pattern): a
    router under a storm needs no extra wiring."""
    monkeypatch.setenv("DACCORD_FAULT", "net_refused:1@healthz")
    netio.install_faults(None)                     # drop any explicit plan
    with pytest.raises(netio.InjectedNetFault) as ei:
        netio.request("http://127.0.0.1:1/healthz", "healthz", timeout=0.2)
    assert ei.value.errno == errno.ECONNREFUSED
    assert ei.value.fault_kind == "net_refused"


# ---------------------------------------------------------------------------
# netio request discipline (real loopback server)
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # noqa: A002
        pass

    def _serve(self):
        srv = self.server
        srv.hits += 1
        beh = srv.script.pop(0) if srv.script else {}
        if beh.get("delay"):
            time.sleep(beh["delay"])
        body = beh.get("body", b'{"ok": true}')
        self.send_response(beh.get("status", 200))
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        declared = beh.get("declared", len(body))
        if declared is not None:
            self.send_header(netio.BODY_BYTES_HEADER, str(declared))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        self._serve()

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length", 0) or 0)
        if n:
            self.rfile.read(n)
        self._serve()


@pytest.fixture
def httpd():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
    srv.daemon_threads = True
    srv.hits = 0
    srv.script = []
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    srv.url = f"http://127.0.0.1:{srv.server_address[1]}"
    yield srv
    srv.shutdown()
    srv.server_close()


def test_request_absorbs_transient_reset(httpd):
    """Injected reset fires BEFORE the request leaves the socket (the
    peer never saw it), wears the real errno, logs ``net.fault``, and the
    bounded retry absorbs it."""
    netio.install_faults(FaultPlan.parse("net_reset:1@submit"))
    events = []
    status, body, _ = netio.request(
        httpd.url + "/v1/jobs", "submit", method="POST", body=b"{}",
        retries=2, log_event=lambda e, **kw: events.append((e, kw)),
        peer="pX")
    assert status == 200 and json.loads(body)["ok"]
    assert httpd.hits == 1                        # fault fired pre-send
    assert events == [("net.fault", {"kind": "net_reset",
                                     "domain": "submit", "peer": "pX"})]


def test_request_non_idempotent_never_retried(httpd):
    """A submit without an idempotency key must surface its reset: only
    the journal-backed key makes the retry exactly-once."""
    netio.install_faults(FaultPlan.parse("net_reset:1@submit"))
    with pytest.raises(netio.InjectedNetFault) as ei:
        netio.request(httpd.url + "/v1/jobs", "submit", method="POST",
                      body=b"{}", retries=3, idempotent=False)
    assert ei.value.errno == errno.ECONNRESET
    assert httpd.hits == 0


def test_injected_hang_bounded_by_deadline(httpd):
    """``net_hang`` surfaces as the DEADLINE timeout, after a bounded
    wall-clock spend — the caller's per-domain deadline is the contract."""
    netio.install_faults(FaultPlan.parse("net_hang:1@healthz"))
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        netio.request(httpd.url + "/healthz", "healthz", timeout=0.25)
    assert time.monotonic() - t0 < 2.0
    assert httpd.hits == 0


def test_torn_body_detected_and_retried_when_idempotent(httpd):
    """A body shorter than the peer's declared byte count is a TornBody —
    retried when idempotent, surfaced when not."""
    httpd.script = [{"declared": 999}, {}]
    status, body, _ = netio.request(httpd.url + "/x", "result", retries=1)
    assert status == 200 and httpd.hits == 2
    httpd.script = [{"declared": 999}]
    with pytest.raises(netio.TornBody):
        netio.request(httpd.url + "/x", "submit", retries=1,
                      idempotent=False)


def test_injected_torn_truncates_and_retry_heals(httpd):
    netio.install_faults(FaultPlan.parse("net_torn:4@result"))
    status, body, _ = netio.request(httpd.url + "/x", "result", retries=1)
    assert status == 200 and body == b'{"ok": true}' and httpd.hits == 2


def test_http_error_status_is_an_answer_not_a_failure(httpd):
    """429/503/404 are VALID answers from a live peer: returned verbatim,
    never retried, never fed to the breaker as transport failures."""
    httpd.script = [{"status": 503, "body": b'{"retryable": true}'}]
    status, body, _ = netio.request(httpd.url + "/x", "result", retries=2)
    assert status == 503 and json.loads(body)["retryable"]
    assert httpd.hits == 1


# ---------------------------------------------------------------------------
# streamed reads: trailer verification
# ---------------------------------------------------------------------------

class _StreamHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # noqa: A002
        pass

    def do_GET(self):  # noqa: N802
        srv = self.server
        self.send_response(200)
        self.send_header("Content-Type", "text/x-fasta")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Trailer", netio.STREAM_BYTES_TRAILER)
        self.end_headers()
        sent = 0
        for c in srv.chunks:
            self.wfile.write(b"%x\r\n" % len(c) + c + b"\r\n")
            sent += len(c)
        declared = srv.declared if srv.declared is not None else sent
        self.wfile.write(b"0\r\n" + netio.STREAM_BYTES_TRAILER.encode()
                         + b": %d\r\n\r\n" % declared)


@pytest.fixture
def stream_srv():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _StreamHandler)
    srv.daemon_threads = True
    srv.chunks = [b"aaaa", b"bbbb"]
    srv.declared = None
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    srv.url = f"http://127.0.0.1:{srv.server_address[1]}"
    yield srv
    srv.shutdown()
    srv.server_close()


def test_stream_trailer_verified(stream_srv):
    status, rhead, gen = netio.stream(stream_srv.url + "/s", "stream")
    assert status == 200
    assert b"".join(gen) == b"aaaabbbb"


def test_stream_trailer_mismatch_raises(stream_srv):
    stream_srv.declared = 999
    _, _, gen = netio.stream(stream_srv.url + "/s", "stream")
    with pytest.raises(netio.TornBody) as ei:
        b"".join(gen)
    assert ei.value.expected == 999 and ei.value.got == 8


def test_stream_injected_torn_partial_then_raises(stream_srv):
    """An injected mid-copy tear: bytes stop at the offset and the
    terminator/trailer never arrives — a consumer can never mistake the
    partial for a complete result."""
    netio.install_faults(FaultPlan.parse("net_torn:6@stream"))
    _, _, gen = netio.stream(stream_srv.url + "/s", "stream")
    got = b""
    with pytest.raises(netio.TornBody):
        for c in gen:
            got += c
    assert got == b"aaaabb"


# ---------------------------------------------------------------------------
# circuit breaker + NetClient discipline
# ---------------------------------------------------------------------------

def test_circuit_breaker_lifecycle():
    t = [0.0]
    br = netio.CircuitBreaker(fails=2, open_s=5.0, clock=lambda: t[0])
    assert br.state() == "closed" and br.allow()
    assert br.fail() is None                       # 1 of 2
    assert br.fail() == "open"                     # threshold: transition
    assert br.state() == "open" and not br.allow()
    t[0] = 5.1
    assert br.state() == "half-open"
    assert br.allow()                              # ONE probe admitted
    assert not br.allow()                          # concurrent: fail fast
    assert br.fail() is None                       # failed probe re-arms
    assert br.state() == "open" and not br.allow()
    t[0] = 10.3
    assert br.state() == "half-open" and br.allow()
    assert br.ok() == "closed"
    assert br.state() == "closed" and br.allow()
    assert br.ok() is None                         # steady state: no event


def test_netclient_breaker_opens_then_recloses(httpd):
    events = []
    nc = netio.NetClient(
        log_event=lambda e, **kw: events.append((e, kw)),
        retries=0, breaker_fails=1, breaker_open_s=0.2)
    netio.install_faults(FaultPlan.parse("net_refused:1@submit"))
    with pytest.raises(netio.InjectedNetFault):
        nc.request("px", httpd.url + "/v1/jobs", "submit", method="POST",
                   body=b"{}", idempotent=False)
    assert nc.breaker_state("px") == "open"
    hits0 = httpd.hits
    with pytest.raises(netio.BreakerOpen):         # open: no socket spend
        nc.request("px", httpd.url + "/v1/jobs", "submit", method="POST",
                   body=b"{}")
    assert httpd.hits == hits0
    time.sleep(0.25)                               # half-open: probe admitted
    status, _, _ = nc.request("px", httpd.url + "/x", "result")
    assert status == 200
    assert nc.breaker_state("px") == "closed"
    states = [kw["state"] for e, kw in events if e == "router.breaker"]
    assert states == ["open", "closed"]
    assert nc.counters["breaker_opens"] == 1


def test_hedged_read_races_grey_slow_peer(httpd):
    """Past the p99-derived budget a second identical request races the
    wedged primary; the earliest answer wins and ``net.hedge`` records
    the countermeasure firing."""
    events = []
    nc = netio.NetClient(
        log_event=lambda e, **kw: events.append((e, kw)),
        hedge_floor_s=0.05, hedge_min_samples=4)
    for _ in range(4):
        nc._note_latency("px", "result", 0.01)
    httpd.script = [{"delay": 0.6}, {}]            # primary wedged, hedge ok
    t0 = time.monotonic()
    status, body, _ = nc.request("px", httpd.url + "/x", "result")
    assert status == 200
    assert time.monotonic() - t0 < 0.5             # did not wait the primary
    assert nc.counters["hedges"] == 1 and nc.counters["hedge_wins"] == 1
    assert any(e == "net.hedge" and kw["domain"] == "result"
               for e, kw in events)


# ---------------------------------------------------------------------------
# router: bounded healthz polls + partition reconciliation (satellite b)
# ---------------------------------------------------------------------------

def _mk_router(tmp_path, **kw):
    from daccord_tpu.serve.router import Router, RouterConfig

    kw.setdefault("poll_s", 3600.0)
    kw.setdefault("peer_dir", str(tmp_path / "fleet"))
    kw.setdefault("workdir", str(tmp_path / "router"))
    os.makedirs(kw["peer_dir"], exist_ok=True)
    return Router(RouterConfig(**kw))


def _events(rt):
    rt.log.flush()
    path = os.path.join(rt.cfg.workdir, "router.events.jsonl")
    with open(path) as fh:
        return [json.loads(l) for l in fh if l.strip()]


class _Healthz(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802
        body = json.dumps({"ok": True, "ready": True}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # noqa: A002
        pass


def test_healthz_hang_poll_bounded_and_partition_cycle(tmp_path):
    """The ISSUE 18 poll-wedge regression: a ``net_hang@healthz`` costs
    ONE bounded deadline — the sweep returns promptly, the unreachable
    peer with a FRESH announce lease is reconciled to PARTITIONED (not
    dead, not removed), and heals to alive on the next clean poll. A
    stale lease, by contrast, removes the peer entirely."""
    from daccord_tpu.utils import lease

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Healthz)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"

    rt = _mk_router(tmp_path, healthz_timeout_s=0.3, lease_ttl_s=60.0)
    os.makedirs(os.path.join(rt.cfg.peer_dir, "peers"), exist_ok=True)
    lp = os.path.join(rt.cfg.peer_dir, "peers", "peer-x.lease")
    lease.claim(lp, "peer-x@test", 60.0, extra={"url": url,
                                                "service": "peer-x"})
    try:
        rt.refresh()
        assert rt.peers["peer-x"].alive

        netio.install_faults(FaultPlan.parse("net_hang:1@healthz"))
        t0 = time.monotonic()
        rt.refresh()
        assert time.monotonic() - t0 < 2.5         # deadline, not a wedge
        p = rt.peers["peer-x"]
        assert not p.alive and p.partitioned       # lease fresh: cut off,
        assert p.lease_age >= 0.0                  # not dead

        netio.install_faults(None)
        rt.refresh()                               # clean poll: healed
        assert p.alive and not p.partitioned

        evs = _events(rt)
        parts = [e for e in evs if e["event"] == "router.partition"]
        assert [e["state"] for e in parts] == ["begin", "end"]
        assert any(e["event"] == "net.fault" and e["kind"] == "net_hang"
                   and e["domain"] == "healthz" for e in evs)

        lease.backdate(lp, 120.0)                  # stale announce: gone
        rt.refresh()
        assert "peer-x" not in rt.peers
        downs = [e for e in _events(rt)
                 if e["event"] == "router.peer_down"]
        assert any(e["reason"] == "lease_stale" for e in downs)
    finally:
        rt.shutdown()
        srv.shutdown()
        srv.server_close()
    _lint([os.path.join(str(tmp_path / "router"), "router.events.jsonl")])


# ---------------------------------------------------------------------------
# router: client disconnect mid-proxied-stream (satellite a regression)
# ---------------------------------------------------------------------------

class _SlowStream(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # noqa: A002
        pass

    def do_GET(self):  # noqa: N802
        self.send_response(200)
        self.send_header("Content-Type", "text/x-fasta")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        sent = 0
        try:
            for _ in range(40):
                c = b"x" * 1024
                self.wfile.write(b"%x\r\n" % len(c) + c + b"\r\n")
                self.wfile.flush()
                sent += len(c)
                time.sleep(0.1)
            self.wfile.write(b"0\r\n" + netio.STREAM_BYTES_TRAILER.encode()
                             + b": %d\r\n\r\n" % sent)
        except (BrokenPipeError, ConnectionResetError):
            pass


def test_client_disconnect_midstream_not_blamed_on_peer(tmp_path):
    """The misclassification bugfix: a DOWNSTREAM client hanging up while
    the router proxies a healthy peer's stream is ``router.client_gone``
    — no ``mark_dead``, no breaker strike, no ``router.peer_down``. One
    tenant's flaky connection must not de-route a peer for everyone."""
    from daccord_tpu.serve.router import Peer, start_router

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _SlowStream)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    peer_url = f"http://127.0.0.1:{srv.server_address[1]}"

    rt = _mk_router(tmp_path)
    rt.peers["px"] = Peer(name="px", url=peer_url, alive=True, ready=True)
    rt._job_map["jx"] = "px"
    rhttpd, rport, _t = start_router(rt)
    try:
        s = socket.create_connection(("127.0.0.1", rport), timeout=10)
        s.sendall(b"GET /v1/jobs/jx/stream HTTP/1.1\r\n"
                  b"Host: localhost\r\n\r\n")
        s.recv(2048)                               # headers + first chunks
        # RST on close so the router's next write fails immediately
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     struct.pack("ii", 1, 0))
        s.close()

        deadline = time.time() + 10
        gone = []
        while time.time() < deadline:
            gone = [e for e in _events(rt)
                    if e["event"] == "router.client_gone"]
            if gone:
                break
            time.sleep(0.1)
        assert gone and gone[0]["peer"] == "px"
        assert gone[0]["path"] == "/v1/jobs/jx/stream"
        assert gone[0]["bytes"] >= 0

        # the peer keeps its routability and its clean breaker
        assert rt.peers["px"].alive
        assert rt.net.breaker_state("px") == "closed"
        evs = _events(rt)
        assert not [e for e in evs if e["event"] == "router.peer_down"]
        assert not [e for e in evs if e["event"] == "router.proxy_error"]
    finally:
        rt.shutdown()
        rhttpd.shutdown()
        srv.shutdown()
        srv.server_close()
    _lint([os.path.join(str(tmp_path / "router"), "router.events.jsonl")])


# ---------------------------------------------------------------------------
# autoscaler: partition reap-safety matrix (satellite c)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def time(self):
        return self.t


class _FakeProc:
    def __init__(self):
        self.pid = 54321
        self.rc = None

    def poll(self):
        return self.rc


def _mk_peer(name, **kw):
    from daccord_tpu.serve.router import Peer

    kw.setdefault("alive", True)
    kw.setdefault("ready", True)
    return Peer(name=name, url=kw.pop("url", f"http://127.0.0.1:1/{name}"),
                **kw)


def _mk_scaler(tmp_path, log, **kw):
    from daccord_tpu.serve import AutoscaleConfig, Autoscaler

    kw.setdefault("peer_dir", str(tmp_path / "fleet"))
    kw.setdefault("root", str(tmp_path / "autopeers"))
    kw.setdefault("backend", "native")
    return Autoscaler(AutoscaleConfig(**kw), log)


def test_autoscaler_never_drains_partitioned_peer(tmp_path, monkeypatch):
    """A fresh-leased unreachable peer is invisible, not idle: its idle
    clock resets every partitioned sweep, so no TTL ever elapses against
    the window — and after healing, the TTL starts FRESH."""
    import daccord_tpu.serve.autoscale as asc

    clock = _Clock(1000.0)
    monkeypatch.setattr(asc, "time", clock)
    log = _CapLog()
    sc = _mk_scaler(tmp_path, log, max_peers=4, min_peers=1,
                    idle_ttl_s=4.0, cooldown_s=3600.0)
    sc.adopt("pp", _FakeProc(), str(tmp_path / "pp"))
    anchor = _mk_peer("p0")                        # keeps live > min_peers
    part = _mk_peer("pp", alive=False, partitioned=True)

    sc.tick([anchor, part])
    clock.t = 1020.0                               # 20s >> idle_ttl
    sc.tick([anchor, part])
    assert sc.counters["drains"] == 0 and not log["scale.drain"]

    healed = _mk_peer("pp")                        # healthz back, idle
    sc.tick([anchor, healed])                      # clock starts NOW
    clock.t = 1023.9
    sc.tick([anchor, healed])
    assert sc.counters["drains"] == 0              # fresh TTL not elapsed
    clock.t = 1024.1
    sc.tick([anchor, healed])                      # ... now it is
    assert sc.counters["drains"] == 1
    assert log["scale.drain"][0]["peer"] == "pp"


def test_partitioned_peer_occupies_spawn_capacity(tmp_path, monkeypatch):
    """Partitioned hardware is alive hardware we merely cannot see: it
    still counts against ``max_peers`` — healing must not land the fleet
    over the cap."""
    import daccord_tpu.serve.autoscale as asc

    clock = _Clock(1000.0)
    monkeypatch.setattr(asc, "time", clock)
    procs = []

    class _FakeSub:
        STDOUT = None

        @staticmethod
        def Popen(cmd, env=None, stdout=None, stderr=None):
            if stdout is not None:
                stdout.close()
            procs.append(cmd)
            return _FakeProc()

    monkeypatch.setattr(asc, "subprocess", _FakeSub)
    log = _CapLog()
    sc = _mk_scaler(tmp_path, log, max_peers=2, min_peers=1,
                    spawn_burn=1.0, sustain_s=1.0, cooldown_s=0.0,
                    idle_ttl_s=0.0)
    hot = _mk_peer("p0", burn=3.0)
    part = _mk_peer("pp", alive=False, partitioned=True)

    sc.tick([hot, part])
    clock.t = 1002.0                               # sustained + cooled ...
    sc.tick([hot, part])
    assert sc.counters["spawns"] == 0              # ... but present == cap
    clock.t = 1003.0
    sc.tick([hot])                                 # partition resolved dead
    assert sc.counters["spawns"] == 1 and len(procs) == 1


def test_drain_call_bounded_and_marks_nothing(tmp_path):
    """A drain whose socket wedges costs one ``abort`` deadline and
    journal-marks NOTHING — the peer's own journal owns its recovery;
    the autoscaler only ever asks politely."""
    log = _CapLog()
    sc = _mk_scaler(tmp_path, log, drain_timeout_s=0.3)
    netio.install_faults(FaultPlan.parse("net_hang:1@abort"))
    t0 = time.monotonic()
    sc._drain("pp", "http://127.0.0.1:1")
    assert time.monotonic() - t0 < 2.0             # bounded, not wedged
    assert sc.counters["drains"] == 1
    assert [e for e, kw in log.events] == ["net.fault", "scale.drain"]
    # unreachable-peer drain (refused) is equally silent
    sc._drain("pq", "http://127.0.0.1:1")
    assert sc.counters["drains"] == 2


# ---------------------------------------------------------------------------
# tool belt: eventcheck schemas + sentinel flags
# ---------------------------------------------------------------------------

def _write_events(path, recs):
    with open(path, "w") as fh:
        for i, r in enumerate(recs):
            fh.write(json.dumps({"t": float(i), "ts": float(i), **r}) + "\n")
    return str(path)


def test_eventcheck_knows_net_kinds(tmp_path):
    from daccord_tpu.tools.eventcheck import validate_events

    good = _write_events(tmp_path / "ok.jsonl", [
        {"event": "net.fault", "kind": "net_reset", "domain": "submit",
         "peer": "pA"},
        {"event": "net.hedge", "peer": "pA", "domain": "result",
         "budget_s": 0.25},
        {"event": "router.breaker", "peer": "pA", "state": "open"},
        {"event": "router.partition", "peer": "pB", "state": "begin",
         "lease_age_s": 0.4},
        {"event": "router.client_gone", "peer": "pA",
         "path": "/v1/jobs/j1/stream", "bytes": 512},
    ])
    assert validate_events(good, strict=True) == []
    bad = _write_events(tmp_path / "bad.jsonl", [
        {"event": "router.partition", "peer": "pB", "state": 3,
         "lease_age_s": "fresh"},
    ])
    assert validate_events(bad, strict=True)


def test_sentinel_flags_partition_and_breaker(tmp_path):
    from daccord_tpu.tools.sentinel import scan_events

    healed = _write_events(tmp_path / "healed.jsonl", [
        {"event": "router.partition", "peer": "pB", "state": "begin",
         "lease_age_s": 0.5},
        {"event": "router.partition", "peer": "pB", "state": "end",
         "lease_age_s": 0.7},
        {"event": "router.breaker", "peer": "pA", "state": "open"},
        {"event": "router.breaker", "peer": "pA", "state": "closed"},
    ])
    issues = scan_events(healed)
    # a partition window is a red flag even when it heals (the disk-
    # pressure precedent): the network needs an operator
    assert any("ASYMMETRIC PARTITION" in s for s in issues)
    assert not any("never re-closed" in s for s in issues)
    assert not any("still partitioned" in s for s in issues)
    assert not any("DURING its partition window" in s for s in issues)

    sick = _write_events(tmp_path / "sick.jsonl", [
        {"event": "router.partition", "peer": "pB", "state": "begin",
         "lease_age_s": 0.5},
        {"event": "scale.reap", "peer": "pB", "rc": -9, "life_s": 12.0},
        {"event": "router.breaker", "peer": "pA", "state": "open"},
    ])
    issues = scan_events(sick)
    assert any("DURING its partition window" in s for s in issues)
    assert any("never re-closed" in s for s in issues)
    assert any("still partitioned" in s for s in issues)


def test_sentinel_bench_chaos_exemption_net():
    from daccord_tpu.tools.sentinel import check_bench_series

    chaos = [("BENCH_NET.json", {"metric": "net_soak", "chaos": True,
                                 "partition_begin": 1, "breaker_open": 2})]
    assert check_bench_series(chaos) == []


# ---------------------------------------------------------------------------
# the full storm (slow rung; the pounce smoke and DACCORD_BENCH_NET=1 run
# the same contract end-to-end)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_net_soak_contract(tmp_path):
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)
    line = bench.run_net_soak(root=str(tmp_path), n_jobs=2,
                              commit_sidecar=False)
    assert line["chaos"] and line["recovered"] and line["parity"]
    assert line["breaker_open"] >= 1 and line["partition_begin"] >= 1
    assert line["drain_or_reap_in_partition"] == 0
    assert line["done"] == line["jobs"]
