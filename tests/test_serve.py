"""Serving plane (daccord_tpu/serve, ISSUE 10): cross-job batching byte
parity under the fault/capacity matrix, admission control, warm state,
latency quantiles, and the job-tagged outcome ledger.

The byte contract under test: N concurrent jobs multiplexed into shared
device batches each produce FASTA byte-identical to their solo ``daccord``
run — including when the shared supervisor fails over (device_lost), when
the capacity governor bisects a mixed-job batch (device_oom), and when a
cohabiting job aborts mid-run. Fast tier runs on the native engine (no XLA
compiles); the JAX-CPU arms (fused, split two-stream, paged wire format)
are the slow tier.
"""

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from daccord_tpu.sim import SimConfig, make_dataset

try:
    from daccord_tpu.native import available as _native_available

    HAVE_NATIVE = _native_available()
except Exception:
    HAVE_NATIVE = False

needs_native = pytest.mark.skipif(not HAVE_NATIVE,
                                  reason="native host path unavailable")


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("serve"))
    cfg = SimConfig(genome_len=1500, coverage=10, read_len_mean=500,
                    min_overlap=200, seed=5)
    return make_dataset(d, cfg, name="sv"), d


def _solo_bytes(out, d, backend="native"):
    """The solo-run reference: the job-config builder's own output (CLI
    parity by construction), run through the stock pipeline."""
    import dataclasses

    from daccord_tpu.runtime.pipeline import correct_to_fasta
    from daccord_tpu.serve.jobs import JobSpec, build_job_config

    spec = JobSpec.from_json({"db": out["db"], "las": out["las"]}, d)
    cfg = build_job_config(spec, backend, True, 64, "fused", d, "solo")
    cfg = dataclasses.replace(cfg, native_solver=backend == "native",
                              supervise=True, events_path=None,
                              ledger_path=None, job_tag=None,
                              quarantine_path=None)
    ref = os.path.join(d, f"solo-{backend}.fasta")
    if not os.path.exists(ref):
        correct_to_fasta(out["db"], out["las"], ref, cfg)
    with open(ref, "rb") as fh:
        return fh.read()


def _svc(workdir, backend="native", **kw):
    from daccord_tpu.serve import ConsensusService, ServeConfig

    kw.setdefault("batch", 64)
    kw.setdefault("workers", 2)
    kw.setdefault("flush_lag_s", 0.02)
    return ConsensusService(ServeConfig(workdir=str(workdir), backend=backend,
                                        backend_explicit=True, **kw))


def _job_fasta(svc, j):
    return open(os.path.join(svc.cfg.workdir, "jobs", j["job"],
                             "out.fasta"), "rb").read()


def _lint(paths):
    from daccord_tpu.tools.eventcheck import validate_events

    for p in paths:
        errs = validate_events(p, strict=True)
        assert not errs, (p, errs[:5])


@needs_native
def test_two_jobs_byte_parity(dataset, tmp_path):
    """Two concurrent jobs through shared batches == two solo runs, with a
    warm-group hit for the second job and lint-clean telemetry."""
    out, d = dataset
    ref = _solo_bytes(out, d)
    svc = _svc(tmp_path / "srv")
    j1 = svc.submit({"db": out["db"], "las": out["las"], "tenant": "a"})
    j2 = svc.submit({"db": out["db"], "las": out["las"], "tenant": "b"})
    s1 = svc.wait(j1["job"], 300)
    s2 = svc.wait(j2["job"], 300)
    st = svc.stats()
    svc.shutdown()
    assert s1["state"] == "done" and s2["state"] == "done", (s1, s2)
    assert _job_fasta(svc, j1) == ref
    assert _job_fasta(svc, j2) == ref
    # one solve fingerprint -> ONE group: the second job was a warm hit
    assert st["warm"]["misses"] == 1 and st["warm"]["hits"] == 1
    # latency quantiles rode the rollup (satellite 1)
    h = st["metrics"]["hists"]["job_latency_s"]
    assert h["count"] == 2 and h["p50"] is not None and h["p99"] is not None
    _lint(glob.glob(os.path.join(svc.cfg.workdir, "*.events.jsonl"))
          + glob.glob(os.path.join(svc.cfg.workdir, "jobs", "*",
                                   "events.jsonl")))


@needs_native
def test_cross_job_merged_batch_unit(dataset, tmp_path):
    """Deterministic mixing: two jobs each pool a sub-width block; the
    flush merges them into ONE device batch (jobs=2) and each handle's
    result is byte-identical to solving its rows alone."""
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.kernels.tensorize import BatchShape, tensorize_windows
    from daccord_tpu.runtime.pipeline import (_sample_windows,
                                              estimate_profile_for_shard)
    from daccord_tpu.serve.batcher import GroupConfig, SolveGroup
    from daccord_tpu.serve.jobs import JobSpec, build_job_config

    out, d = dataset
    db = read_db(out["db"])
    las = LasFile(out["las"])
    spec = JobSpec.from_json({"db": out["db"], "las": out["las"]}, str(d))
    cfg = build_job_config(spec, "native", True, 64, "fused",
                           str(tmp_path), "unit")
    profile = estimate_profile_for_shard(db, las, cfg)
    _, windows = _sample_windows(db, las, cfg, None, None)
    assert len(windows) >= 80, "sample too small for the unit"
    shape = BatchShape(depth=cfg.depth, seg_len=cfg.seg_len,
                       wlen=cfg.consensus.w)
    full = tensorize_windows([(0, ws) for ws in windows[:80]], shape)
    from daccord_tpu.kernels.tensorize import slice_batch

    a, b = slice_batch(full, 0, 40), slice_batch(full, 40, 80)

    group = SolveGroup("k", profile, cfg,
                       GroupConfig(backend="native", batch=64))
    sa, sb = group.job_solver("jobA"), group.job_solver("jobB")
    ha = sa.dispatch(a)          # 40 rows pooled, below width
    assert group.counters["batches"] == 0
    hb = sb.dispatch(b)          # 80 rows -> one 64-row merged flush
    assert group.counters["batches"] == 1
    assert group.counters["mixed_batches"] == 1
    ra, rb = sa.fetch(ha), sb.fetch(hb)
    assert len(ra["solved"]) == 40 and len(rb["solved"]) == 40

    # solo control: a second group solves each block alone
    solo = SolveGroup("k2", profile, cfg,
                      GroupConfig(backend="native", batch=64))
    ss = solo.job_solver("solo")
    for blk, res in ((a, ra), (b, rb)):
        ctrl = ss.fetch(ss.dispatch(blk))
        for k in ("solved", "tier", "cons_len", "err"):
            np.testing.assert_array_equal(np.asarray(ctrl[k]),
                                          np.asarray(res[k]), err_msg=k)
        # consensus bytes row by row (trailing capacity may differ)
        for i in range(blk.size):
            n = int(ctrl["cons_len"][i])
            np.testing.assert_array_equal(
                np.asarray(ctrl["cons"][i][:n]),
                np.asarray(res["cons"][i][:n]))


@needs_native
def test_device_oom_bisects_mixed_batches(dataset, tmp_path, monkeypatch):
    """Injected device OOM classifies on the SHARED supervisor and the
    governor bisects merged (mixed-job) batches — bytes unchanged."""
    out, d = dataset
    ref = _solo_bytes(out, d)
    monkeypatch.setenv("DACCORD_FAULT", "device_oom:2")
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    svc = _svc(tmp_path / "srv")
    j1 = svc.submit({"db": out["db"], "las": out["las"], "tenant": "a"})
    j2 = svc.submit({"db": out["db"], "las": out["las"], "tenant": "b"})
    s1 = svc.wait(j1["job"], 300)
    s2 = svc.wait(j2["job"], 300)
    st = svc.stats()
    svc.shutdown()
    assert s1["state"] == "done" and s2["state"] == "done", (s1, s2)
    g = st["warm"]["groups"][0]
    assert g["governor"]["classify"] >= 1 and g["governor"]["shrink"] >= 1
    assert not g["degraded"], "capacity must degrade, never fail over"
    assert _job_fasta(svc, j1) == ref
    assert _job_fasta(svc, j2) == ref


@needs_native
def test_device_lost_fails_over_all_jobs(dataset, tmp_path, monkeypatch):
    """Declared device loss mid-serve: the shared supervisor replays every
    in-flight merged batch on the fallback engine; every job's bytes hold."""
    out, d = dataset
    ref = _solo_bytes(out, d)
    monkeypatch.setenv("DACCORD_FAULT", "device_lost:2")
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    svc = _svc(tmp_path / "srv")
    j1 = svc.submit({"db": out["db"], "las": out["las"]})
    j2 = svc.submit({"db": out["db"], "las": out["las"]})
    s1 = svc.wait(j1["job"], 300)
    s2 = svc.wait(j2["job"], 300)
    st = svc.stats()
    svc.shutdown()
    assert s1["state"] == "done" and s2["state"] == "done", (s1, s2)
    assert st["warm"]["groups"][0]["degraded"]
    assert _job_fasta(svc, j1) == ref
    assert _job_fasta(svc, j2) == ref


@needs_native
def test_abort_does_not_poison_cohabitants(dataset, tmp_path):
    """A mid-run client abort drops the job without changing one byte of
    the cohabiting job's output (the batcher's release contract)."""
    out, d = dataset
    ref = _solo_bytes(out, d)
    svc = _svc(tmp_path / "srv")
    ja = svc.submit({"db": out["db"], "las": out["las"], "tenant": "a"})
    jb = svc.submit({"db": out["db"], "las": out["las"], "tenant": "b"})

    def chase():
        while True:
            s = svc.status(ja["job"])
            if s is None or s["state"] in ("done", "failed", "aborted"):
                return
            if s["state"] == "running" and s["reads"] > 2:
                svc.abort(ja["job"])
                return
            time.sleep(0.005)

    t = threading.Thread(target=chase)
    t.start()
    sa = svc.wait(ja["job"], 300)
    sb = svc.wait(jb["job"], 300)
    t.join()
    svc.shutdown()
    assert sb["state"] == "done", sb
    assert sa["state"] in ("aborted", "done"), sa   # may have won the race
    assert _job_fasta(svc, jb) == ref


def test_admission_quotas_and_pressure():
    from daccord_tpu.runtime.faults import FaultPlan
    from daccord_tpu.serve import (AdmissionConfig, AdmissionController,
                                   AdmissionReject)

    ctl = AdmissionController(AdmissionConfig(tenant_max_queued=1,
                                              tenant_max_bytes=100,
                                              max_queued_jobs=3))
    ctl.admit("a", 10, job="j1")
    with pytest.raises(AdmissionReject) as ei:
        ctl.admit("a", 10, job="j2")
    assert ei.value.reason == "quota_jobs"
    with pytest.raises(AdmissionReject) as ei:
        ctl.admit("b", 1000, job="j3")
    assert ei.value.reason == "quota_bytes"
    ctl.release("a", 10)
    ctl.admit("a", 10, job="j4")          # slot freed
    # injected host pressure pauses admission deterministically
    ctl2 = AdmissionController(AdmissionConfig(),
                               faults=FaultPlan.parse("host_rss:1"))
    with pytest.raises(AdmissionReject) as ei:
        ctl2.admit("a", 1, job="j5")
    assert ei.value.reason == "pressure" and ei.value.retryable
    ctl2.admit("a", 1, job="j6")          # one-shot injection consumed
    # draining refuses everything
    ctl.drain()
    with pytest.raises(AdmissionReject) as ei:
        ctl.admit("c", 1, job="j7")
    assert ei.value.reason == "draining"
    st = ctl.stats()
    assert st["rejected"] == 3 and st["admitted"] == 2


@needs_native
def test_restart_never_reuses_job_ids(dataset, tmp_path):
    """A restarted server on the same (durable) workdir resumes the job-id
    sequence past existing job dirs — reusing jNNNNN would serve or clobber
    the previous run's committed result (review finding)."""
    out, d = dataset
    svc = _svc(tmp_path / "srv", workers=1)
    j1 = svc.submit({"db": out["db"], "las": out["las"]})
    svc.wait(j1["job"], 300)
    svc.shutdown()
    assert j1["job"] == "j00001"
    svc2 = _svc(tmp_path / "srv", workers=1)
    j2 = svc2.submit({"db": out["db"], "las": out["las"]})
    svc2.wait(j2["job"], 300)
    svc2.shutdown()
    assert j2["job"] == "j00002"
    # the first run's durable commit is untouched
    assert os.path.exists(os.path.join(str(tmp_path / "srv"), "jobs",
                                       "j00001", "out.fasta"))


def test_rejected_submit_leaves_no_residue(dataset, tmp_path):
    """A refused submission (quota or bad spec) releases its admission
    charge AND leaves no spooled upload behind — rejected requests must not
    grow the workdir (review finding)."""
    import base64

    from daccord_tpu.serve import AdmissionConfig, AdmissionReject

    out, d = dataset
    svc = _svc(tmp_path / "srv", workers=1,
               admission=AdmissionConfig(tenant_max_queued=0))
    up = {"db": "u.db", "las": "u.las",
          "files": {"u.db": base64.b64encode(b"x" * 64).decode(),
                    "u.las": base64.b64encode(b"y" * 64).decode()}}
    with pytest.raises(AdmissionReject):
        svc.submit(up)
    assert os.listdir(os.path.join(svc.cfg.workdir, "jobs")) == []
    assert svc.admission.stats()["queued"] == 0
    # bad spec AFTER admission: charge released, spool removed
    svc2 = _svc(tmp_path / "srv2", workers=1)
    with pytest.raises(ValueError):
        svc2.submit({"db": out["db"], "las": out["las"], "bogus": 1})
    assert os.listdir(os.path.join(svc2.cfg.workdir, "jobs")) == []
    assert svc2.admission.stats()["queued"] == 0
    svc.shutdown()
    svc2.shutdown()


@needs_native
def test_warm_state_reuse_and_eviction(dataset, tmp_path):
    out, d = dataset
    svc = _svc(tmp_path / "srv", idle_evict_s=3600.0, workers=1)
    j1 = svc.submit({"db": out["db"], "las": out["las"]})
    svc.wait(j1["job"], 300)
    j2 = svc.submit({"db": out["db"], "las": out["las"]})
    svc.wait(j2["job"], 300)
    assert svc.warm.counters == {"hits": 1, "misses": 1, "evicted": 0,
                                 "evict_deferred": 0}
    assert len(svc.warm.groups()) == 1
    svc.warm.idle_evict_s = 0.0
    # The ticker also calls evict_idle(); once the TTL drops to 0 either
    # thread may win the eviction, so assert on the counter, not the return.
    svc.warm.evict_idle()
    assert svc.warm.counters["evicted"] == 1
    assert not svc.warm.groups()
    svc.shutdown()


def test_histogram_quantiles():
    from daccord_tpu.utils.obs import MetricsRegistry, _Histogram

    h = _Histogram()
    assert h.summary()["p50"] is None
    for v in range(1, 101):                 # 1..100
        h.observe(float(v))
    s = h.summary()
    assert s["p50"] == 51.0 and s["p95"] == 96.0 and s["p99"] == 100.0
    assert s["count"] == 100 and s["min"] == 1.0 and s["max"] == 100.0
    # beyond the reservoir the estimate stays sane (deterministic seed)
    h2 = _Histogram()
    for v in range(10_000):
        h2.observe(float(v))
    s2 = h2.summary()
    assert 3_000 < s2["p50"] < 7_000, s2
    # quantiles ride the registry snapshot + rollup
    reg = MetricsRegistry()
    reg.histogram("lat").observe(2.0)
    roll = reg.rollup()
    assert roll["hists"]["lat"]["p50"] == 2.0
    assert roll["hists"]["lat"]["p99"] == 2.0


@needs_native
def test_ledger_job_field(dataset, tmp_path):
    """Ledger rows carry the job tag; daccord-trace's reconciliation keys
    dedupe on (job, aread, widx) so merged multi-job ledgers don't
    collapse."""
    import dataclasses

    from daccord_tpu.runtime.pipeline import PipelineConfig, correct_to_fasta
    from daccord_tpu.serve.jobs import JobSpec, build_job_config
    from daccord_tpu.tools.trace import ledger_rows

    out, d = dataset
    led = str(tmp_path / "a.ledger.jsonl")
    spec = JobSpec.from_json({"db": out["db"], "las": out["las"]}, str(d))
    cfg = build_job_config(spec, "native", True, 64, "fused",
                           str(tmp_path), "jobA")
    cfg = dataclasses.replace(cfg, native_solver=True, supervise=True,
                              events_path=None, ledger_path=led,
                              quarantine_path=None)
    st = correct_to_fasta(out["db"], out["las"],
                          str(tmp_path / "a.fasta"), cfg)
    rows = [json.loads(ln) for ln in open(led)]
    assert rows and all(r["job"] == "jobA" for r in rows)
    assert len(rows) == st.n_windows
    # two jobs' ledgers concatenated: distinct count keys on the job tag
    merged = str(tmp_path / "m.ledger.jsonl")
    with open(merged, "wt") as fh:
        for ln in open(led):
            fh.write(ln)
        for ln in open(led):
            fh.write(ln.replace('"job": "jobA"', '"job": "jobB"'))
    total, distinct = ledger_rows(merged)
    assert total == 2 * st.n_windows and distinct == 2 * st.n_windows


def test_job_spec_validation(tmp_path, dataset):
    import base64

    from daccord_tpu.serve.jobs import JobSpec

    out, d = dataset
    with pytest.raises(ValueError, match="missing 'db'"):
        JobSpec.from_json({"las": out["las"]}, str(tmp_path))
    with pytest.raises(ValueError, match="unknown job fields"):
        JobSpec.from_json({"db": out["db"], "las": out["las"],
                           "bogus": 1}, str(tmp_path))
    with pytest.raises(ValueError, match="supported range"):
        JobSpec.from_json({"db": out["db"], "las": out["las"], "k": 99},
                          str(tmp_path))
    with pytest.raises(ValueError, match="not found"):
        JobSpec.from_json({"db": out["db"], "las": "/nope.las"},
                          str(tmp_path))
    # upload mode: b64 files spool into the job dir
    payload = {"db": "up.db", "las": "up.las",
               "files": {"up.db": base64.b64encode(b"x").decode(),
                         "up.las": base64.b64encode(b"y").decode()}}
    spec = JobSpec.from_json(payload, str(tmp_path / "spool"))
    assert spec.uploaded and os.path.exists(spec.db)
    assert open(spec.las, "rb").read() == b"y"


@needs_native
def test_http_end_to_end(dataset, tmp_path):
    """The real HTTP surface: submit, wait, result parity, metrics with
    quantiles, DELETE abort, graceful shutdown."""
    import urllib.error
    import urllib.request

    from daccord_tpu.serve.http import start_server

    out, d = dataset
    ref = _solo_bytes(out, d)
    svc = _svc(tmp_path / "srv")
    httpd, port, _t = start_server(svc, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{port}"

    def req(method, path, body=None):
        r = urllib.request.Request(
            base + path, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=300) as resp:
            return resp.status, resp.read()

    code, b = req("POST", "/v1/jobs", {"db": out["db"], "las": out["las"]})
    assert code == 201
    j = json.loads(b)["job"]
    code, fasta = req("GET", f"/v1/jobs/{j}/result?wait=1")
    assert code == 200 and fasta == ref
    with pytest.raises(urllib.error.HTTPError) as ei:
        req("POST", "/v1/jobs", {"las": out["las"]})
    assert ei.value.code == 400
    # wrong-typed field must be a 400, never a dropped connection
    with pytest.raises(urllib.error.HTTPError) as ei:
        req("POST", "/v1/jobs", {"db": out["db"], "las": out["las"],
                                 "k": "8"})
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        req("GET", f"/v1/jobs/{j}/result?wait=1&timeout=abc")
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        req("GET", "/v1/jobs/nope")
    assert ei.value.code == 404
    code, m = req("GET", "/v1/metrics")
    m = json.loads(m)
    assert m["metrics"]["hists"]["job_latency_s"]["p50"] is not None
    # a second job, aborted over the wire
    code, b = req("POST", "/v1/jobs", {"db": out["db"], "las": out["las"]})
    j2 = json.loads(b)["job"]
    req("DELETE", f"/v1/jobs/{j2}")
    st = svc.wait(j2, 300)
    assert st["state"] in ("aborted", "done")
    code, _ = req("POST", "/v1/shutdown")
    assert code == 200
    for _ in range(200):
        if svc.admission.stats()["draining"]:
            break
        time.sleep(0.05)
    httpd.server_close()


@needs_native
def test_strict_ingest_rejected_at_admission(dataset, tmp_path):
    """A corrupt LAS under strict policy is refused at submit time with the
    structured report — it never costs a queue slot."""
    import shutil

    from daccord_tpu.runtime import faults

    out, d = dataset
    bad_las = str(tmp_path / "bad.las")
    shutil.copy(out["las"], bad_las)
    for ext in (".db", ".idx", ".bps"):
        src = out["db"][:-3] + ext if out["db"].endswith(".db") else \
            out["db"] + ext
        if os.path.exists(src):
            shutil.copy(src, str(tmp_path / ("bad" + ext)))
    faults.corrupt_las_bitflip(bad_las, 4)
    svc = _svc(tmp_path / "srv", workers=1)
    with pytest.raises(ValueError, match="ingest validation"):
        svc.submit({"db": str(tmp_path / "bad.db"), "las": bad_las})
    assert svc.admission.stats()["queued"] == 0
    svc.shutdown()


# ---------------------------------------------------------------------------
# slow tier: the JAX-CPU arms (XLA ladder compiles)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["fused", "split", "paged"])
def test_jax_cpu_arm_byte_parity(dataset, tmp_path, mode):
    """Cross-job batching through the jitted ladder paths: fused dense,
    split two-stream (stream-routed merged pools), and the ragged paged
    wire format — each byte-identical to the solo cpu run."""
    out, d = dataset
    ref = _solo_bytes(out, d, backend="cpu")
    svc = _svc(tmp_path / "srv", backend="cpu", batch=32,
               ladder_mode="split" if mode == "split" else "fused",
               paged=mode == "paged", flush_lag_s=0.05)
    j1 = svc.submit({"db": out["db"], "las": out["las"]})
    j2 = svc.submit({"db": out["db"], "las": out["las"]})
    s1 = svc.wait(j1["job"], 900)
    s2 = svc.wait(j2["job"], 900)
    svc.shutdown()
    assert s1["state"] == "done" and s2["state"] == "done", (s1, s2)
    assert _job_fasta(svc, j1) == ref
    assert _job_fasta(svc, j2) == ref
