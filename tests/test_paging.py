"""Ragged paged window batching (ISSUE 7): round-trip/gather parity, shape
families, and the paged pipeline's byte identity under the fault matrix.

Fast tier: the pack/unpack round-trip property over random ragged piles,
device-gather parity (jnp + Pallas interpret), shape-family derivation and
routing units, paged slice/pad (the governor's bisect primitives), the
supervisor's ``:pg`` shape keys, CLI/schema surfaces — no XLA ladder
compiles. Slow tier: paged output byte-identical to dense on the cfg2-style
corpus with a >=2x pad-waste (dead cells per used cell) reduction, and the
DACCORD_FAULT matrix on the paged path (device_lost failover replay,
device_oom governor bisect of a paged batch, worker_crash mid-shard resume).
"""

import json
import os

import numpy as np
import pytest

from daccord_tpu.kernels import paging
from daccord_tpu.kernels.tensorize import (BatchShape, WindowBatch, pad_batch,
                                           slice_batch, tensorize_windows)
from daccord_tpu.oracle.windows import WindowSegments

# ---------------------------------------------------------------- fast tier


def _ragged_batch(seed=0, b=23, depth=8, seg_len=64, max_seg=70, max_nseg=10):
    """Random ragged piles -> dense WindowBatch (zero-length segments,
    empty windows, and depth-capped windows all represented)."""
    rng = np.random.default_rng(seed)
    shape = BatchShape(depth=depth, seg_len=seg_len, wlen=40)
    items = []
    for i in range(b):
        nseg = int(rng.integers(0, max_nseg))
        segs = [rng.integers(0, 4, size=int(rng.integers(0, max_seg)))
                .astype(np.int8) for _ in range(nseg)]
        items.append((i, WindowSegments(wstart=i * 10, wlen=40,
                                        segments=segs, breads=[0] * nseg)))
    return tensorize_windows(items, shape)


def _covering_family(dense, depth=None):
    pg = paging.window_pages(dense.lens)
    top = max(int(pg.max(initial=1)), 1)
    return paging.ShapeFamily(depth=depth or dense.shape.depth,
                              pages=1 << (top - 1).bit_length())


def test_roundtrip_property():
    """pack -> unpack == dense tensorize, bit for bit, across seeds and
    shapes (the invariant that lets the whole fault/capacity matrix verify
    the paged path)."""
    for seed, depth, seg_len in ((0, 8, 64), (1, 32, 64), (2, 4, 32),
                                 (3, 8, 64)):
        dense = _ragged_batch(seed=seed, depth=depth, seg_len=seg_len)
        fam = _covering_family(dense)
        pb = paging.pack_paged(dense, fam)
        rt = paging.unpack_paged(pb)
        np.testing.assert_array_equal(rt.seqs, dense.seqs)
        np.testing.assert_array_equal(rt.lens, dense.lens)
        np.testing.assert_array_equal(rt.nsegs, dense.nsegs)
        np.testing.assert_array_equal(rt.read_ids, dense.read_ids)
        np.testing.assert_array_equal(rt.wstarts, dense.wstarts)
    # padded pack: sentinel rows unpack to all-PAD windows
    dense = _ragged_batch(seed=4)
    pb = paging.pack_paged(dense, _covering_family(dense), target_rows=32)
    assert pb.size == 32
    d2 = pb.to_dense()
    np.testing.assert_array_equal(d2.seqs[: dense.size], dense.seqs)
    assert (d2.seqs[dense.size:] == 4).all()
    assert (d2.read_ids[dense.size:] == -1).all()


def test_gather_parity():
    """Device-side gather (jnp take fallback AND the Pallas kernel in
    interpret mode) reconstructs the exact dense tile."""
    import jax.numpy as jnp

    dense = _ragged_batch(seed=7, b=16)
    pb = paging.pack_paged(dense, _covering_family(dense))
    for use_pallas in (False, True):
        got = paging.gather_windows(
            jnp.asarray(pb.pool), jnp.asarray(pb.table), jnp.asarray(pb.lens),
            page_len=pb.family.page_len, seg_len=dense.shape.seg_len,
            use_pallas=use_pallas, interpret=use_pallas)
        np.testing.assert_array_equal(np.asarray(got), dense.seqs,
                                      f"use_pallas={use_pallas}")


def test_pack_invariant_violations_raise():
    dense = _ragged_batch(seed=1, depth=8)
    pg = paging.window_pages(dense.lens)
    small = paging.ShapeFamily(depth=8, pages=max(int(pg.max()) - 1, 1))
    with pytest.raises(ValueError, match="page budget"):
        paging.pack_paged(dense, small)
    with pytest.raises(ValueError, match="depth"):
        paging.pack_paged(dense, paging.ShapeFamily(depth=4, pages=1024))
    with pytest.raises(ValueError, match="divide"):
        paging.pack_paged(dense, paging.ShapeFamily(depth=8, pages=1024,
                                                    page_len=24))
    # a pool budget too small for the batch is a router bug, not a silent
    # truncation
    fam = _covering_family(dense)
    tight = paging.ShapeFamily(depth=fam.depth, pages=fam.pages,
                               pool_pages=1)
    with pytest.raises(ValueError, match="pool budget"):
        paging.pack_paged(dense, tight)


def test_family_derivation_units():
    rng = np.random.default_rng(3)
    nsegs = np.concatenate([rng.integers(2, 8, 50),
                            rng.integers(20, 30, 50)])
    pages = np.concatenate([rng.integers(2, 12, 50),
                            rng.integers(50, 90, 50)])
    fams = paging.derive_families(nsegs, pages, max_depth=32, max_pages=128,
                                  budget=4)
    assert 1 <= len(fams) <= 4
    # pow2 quantization + mandatory full coverage
    for f in fams:
        assert f.depth & (f.depth - 1) == 0
        assert f.pages & (f.pages - 1) == 0
        assert 0 < f.budget <= f.pages
    assert fams[-1].depth >= 32 and fams[-1].pages >= 128
    # router order: sorted by pages, every window fits its family, and the
    # assignment is the cheapest fit
    assert [f.pages for f in fams] == sorted(f.pages for f in fams)
    ai = paging.assign_family(fams, nsegs, pages)
    for i, fi in enumerate(ai):
        f = fams[fi]
        assert nsegs[i] <= f.depth and pages[i] <= f.pages
        for fj in range(fi):
            assert not (nsegs[i] <= fams[fj].depth
                        and pages[i] <= fams[fj].pages)
    # derivation is deterministic
    fams2 = paging.derive_families(nsegs, pages, max_depth=32, max_pages=128,
                                   budget=4)
    assert fams == fams2
    # empty sample still yields the covering family
    fams0 = paging.derive_families(np.zeros(0), np.zeros(0), max_depth=32,
                                   max_pages=128, budget=4)
    assert fams0 and fams0[-1].pages >= 128
    # an unroutable window raises instead of truncating
    with pytest.raises(ValueError, match="fits no family"):
        paging.assign_family(fams, np.asarray([64]), np.asarray([500]))
    # non-pow2 structural maxima (e.g. --depth 24): the full-coverage
    # family is capped at the EXACT maxima, never rounded up past the
    # feeder's tensor depth
    fams24 = paging.derive_families(np.minimum(nsegs, 24),
                                    np.minimum(pages, 90),
                                    max_depth=24, max_pages=96, budget=4)
    assert fams24[-1].depth == 24 and fams24[-1].pages == 96
    assert all(f.depth <= 24 and f.pages <= 96 for f in fams24)
    dense24 = _ragged_batch(seed=9, depth=24, max_nseg=26)
    fam24 = fams24[-1]
    pb = paging.pack_paged(dense24, fam24)      # must not raise
    np.testing.assert_array_equal(pb.to_dense().seqs, dense24.seqs)


def test_paged_slice_pad_dispatch():
    """tensorize.slice_batch/pad_batch route paged batches to the table-row
    forms (the governor's bisect rung primitives): pool shared, stream and
    family preserved, round-trip intact."""
    dense = _ragged_batch(seed=5)
    pb = paging.pack_paged(dense, _covering_family(dense))
    pb.stream = "rescue"
    s = slice_batch(pb, 3, 9)
    assert s.size == 6 and s.stream == "rescue" and s.family is pb.family
    assert s.pool is pb.pool            # shared, not copied
    np.testing.assert_array_equal(s.to_dense().seqs, dense.seqs[3:9])
    p = pad_batch(s, 8)
    assert p.size == 8 and p.stream == "rescue"
    d = p.to_dense()
    np.testing.assert_array_equal(d.seqs[:6], dense.seqs[3:9])
    assert (d.seqs[6:] == 4).all() and (d.nsegs[6:] == 0).all()


def test_supervisor_paged_shape_key(tmp_path, monkeypatch):
    """Paged batches fingerprint with the :pg suffix (and :t0 for Stream A)
    so paged and dense programs of the same width classify separately."""
    from daccord_tpu.runtime.supervisor import DeviceSupervisor

    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    sup = DeviceSupervisor(lambda b: b, lambda h: h, describe="stub")
    dense = _ragged_batch(seed=6, b=4)
    pb = paging.pack_paged(dense, _covering_family(dense))
    key = sup._shape_key(pb)
    assert key.endswith(":pg") and "x16xN" in key and "B4x" in key
    pb.stream = "tier0"
    assert sup._shape_key(pb).endswith(":pg:t0")
    # dense keys are untouched
    assert sup._shape_key(dense) == "B4xD8xL64"


def test_degraded_solve_unpacks_paged(tmp_path, monkeypatch):
    """A failed-over supervisor replays a retained PAGED batch on the dense
    fallback engine via to_dense — the engine sees exact dense rows."""
    from daccord_tpu.runtime.faults import FaultPlan
    from daccord_tpu.runtime.supervisor import (DeviceSupervisor,
                                                SupervisorConfig)

    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    seen = {}

    def fallback(b):
        seen["type"] = type(b).__name__
        seen["seqs"] = np.array(b.seqs)
        return {"ok": True}

    sup = DeviceSupervisor(
        lambda b: ("h", b), lambda h: h,
        fallback_factory=lambda: fallback,
        cfg=SupervisorConfig(backoff_base_s=0.01),
        faults=FaultPlan.parse("device_lost:1"), describe="stub")
    dense = _ragged_batch(seed=8, b=4)
    pb = paging.pack_paged(dense, _covering_family(dense))
    h = sup.dispatch(pb)     # op 1: device lost -> failover
    assert sup.failed_over
    assert sup.fetch(h) == {"ok": True}
    assert seen["type"] == "WindowBatch"
    np.testing.assert_array_equal(seen["seqs"], dense.seqs)


def test_eventcheck_paged_schema(tmp_path):
    from daccord_tpu.tools.eventcheck import validate_events

    good = tmp_path / "pg.jsonl"
    good.write_text(
        json.dumps({"t": 0.1, "ts": 1.0, "event": "paging.family",
                    "family": "D8xP16x16b13", "bucket": 0, "depth": 8,
                    "pages": 16, "page_len": 16, "pool_pages": 13}) + "\n"
        + json.dumps({"t": 0.2, "ts": 1.1, "event": "batch.paged",
                      "windows": 32, "bucket": 0, "family": "D8xP16x16b13",
                      "pages": 300, "pool_pages": 416, "table_cells": 2048,
                      "occupancy": 0.72}) + "\n")
    assert validate_events(str(good), strict=True) == []
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(
        {"t": 0.1, "ts": 1.0, "event": "batch.paged", "windows": 32}) + "\n")
    errs = validate_events(str(bad))
    assert errs and any("pool_pages" in e for e in errs)


def test_cli_paged_flag_validation():
    from daccord_tpu.tools.cli import daccord_main

    with pytest.raises(SystemExit, match="paged"):
        daccord_main(["db", "las", "--paged", "on", "--backend", "native"])
    with pytest.raises(SystemExit, match="page-len"):
        daccord_main(["db", "las", "--paged", "on", "--page-len", "24"])


# ---------------------------------------------------------------- slow tier
# (XLA ladder compiles; byte parity + the pad-waste bar are the acceptance)


@pytest.fixture(scope="module")
def cfg2ish(tmp_path_factory):
    """cfg2-style synthetic corpus (production-like depth: the regime the
    >=2x pad-waste acceptance is judged on)."""
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path_factory.mktemp("paged_e2e"))
    cfg = SimConfig(genome_len=4000, coverage=26, read_len_mean=800,
                    min_overlap=300, seed=23)
    return make_dataset(d, cfg, name="c2"), d


@pytest.fixture(scope="module")
def smallish(tmp_path_factory):
    """Small corpus for the fault-matrix arms (bounds compile wall)."""
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path_factory.mktemp("paged_faults"))
    cfg = SimConfig(genome_len=1500, coverage=10, read_len_mean=500,
                    min_overlap=200, seed=5)
    return make_dataset(d, cfg, name="pf"), d


def _pipe_cfg(**kw):
    from daccord_tpu.runtime import PipelineConfig

    kw.setdefault("batch_size", 64)
    return PipelineConfig(**kw)


@pytest.mark.slow
def test_paged_vs_dense_byte_parity_and_waste(cfg2ish):
    """ISSUE 7 acceptance: paged FASTA byte-identical to dense on the
    cfg2-style corpus, pad-waste (dead cells per used cell) drops >= 2x vs
    the default dense bucketing, and every paged dispatch leaves lint-clean
    paging events. Split mode composes on top, byte-identical too."""
    from daccord_tpu.runtime import correct_to_fasta
    from daccord_tpu.tools.eventcheck import validate_events

    out, d = cfg2ish
    f_dense = os.path.join(d, "dense.fasta")
    f_paged = os.path.join(d, "paged.fasta")
    ev = os.path.join(d, "paged.events.jsonl")
    s_dense = correct_to_fasta(out["db"], out["las"], f_dense, _pipe_cfg())
    s_paged = correct_to_fasta(out["db"], out["las"], f_paged,
                               _pipe_cfg(paged="on", events_path=ev))
    assert open(f_dense).read() == open(f_paged).read()
    assert s_paged.paged and not s_dense.paged

    dead_dense = s_dense.pad_waste / (1 - s_dense.pad_waste)
    dead_paged = s_paged.pad_waste / (1 - s_paged.pad_waste)
    assert dead_dense >= 2.0 * dead_paged, (s_dense.pad_waste,
                                            s_paged.pad_waste)

    assert validate_events(ev, strict=True) == []
    recs = [json.loads(x) for x in open(ev)]
    fams = [r for r in recs if r["event"] == "paging.family"]
    dispatches = [r for r in recs if r["event"] == "batch.paged"]
    assert fams and dispatches
    # every dispatch's pages fit its family's static pool
    for r in dispatches:
        assert 0 < r["pages"] <= r["pool_pages"]

    # split-ladder composition: Stream B pools re-pack as paged batches
    f_split = os.path.join(d, "split_paged.fasta")
    s_split = correct_to_fasta(out["db"], out["las"], f_split,
                               _pipe_cfg(paged="on", ladder_mode="split"))
    assert open(f_split).read() == open(f_dense).read()
    assert s_split.n_dispatch_rescue > 0


@pytest.mark.slow
def test_paged_fault_matrix(smallish, monkeypatch):
    """DACCORD_FAULT matrix on the paged path: transient retries, declared
    device loss (both streams' paged batches replay on the dense fallback),
    and a device OOM that bisects a PAGED batch down the governor ladder —
    FASTA byte-identical to the unfaulted dense run throughout."""
    from daccord_tpu.runtime import correct_to_fasta
    from daccord_tpu.tools.eventcheck import validate_events

    out, d = smallish
    monkeypatch.setenv("DACCORD_COMPCACHE", os.path.join(d, "cc"))
    ref = os.path.join(d, "ref.fasta")
    correct_to_fasta(out["db"], out["las"], ref, _pipe_cfg(batch_size=32))
    ref_bytes = open(ref).read()
    monkeypatch.setenv("DACCORD_SUP_BACKOFF_S", "0.01")
    for fault, expect_degraded in (("dispatch_error:2", False),
                                   ("device_lost:3", True),
                                   ("device_oom:2", False)):
        monkeypatch.setenv("DACCORD_FAULT", fault)
        name = fault.split(":")[0]
        f = os.path.join(d, f"{name}.fasta")
        ev = os.path.join(d, f"{name}.events.jsonl")
        st = correct_to_fasta(out["db"], out["las"], f,
                              _pipe_cfg(batch_size=32, paged="on",
                                        events_path=ev))
        assert open(f).read() == ref_bytes, fault
        assert st.degraded == expect_degraded, fault
        assert validate_events(ev, strict=True) == []
        if name == "device_oom":
            evs = [json.loads(x) for x in open(ev)]
            cls = [e for e in evs if e["event"] == "governor.classify"]
            assert cls and all(":pg" in e["key"] for e in cls)
            assert any(e["event"] == "governor.shrink" for e in evs)
            assert not any(e["event"] == "sup_failover" for e in evs)
            assert not st.degraded
    monkeypatch.delenv("DACCORD_FAULT")


@pytest.mark.slow
def test_paged_worker_crash_resume(smallish, monkeypatch):
    """Mid-shard crash + checkpoint resume with the paged wire format: the
    resumed shard reproduces the uninterrupted run's exact bytes."""
    from daccord_tpu.parallel.launch import run_shard, shard_paths
    from daccord_tpu.runtime.faults import InjectedCrash

    out, d = smallish
    monkeypatch.setenv("DACCORD_COMPCACHE", os.path.join(d, "cc"))

    def cfg():
        return _pipe_cfg(batch_size=32, paged="on")

    ref_dir = os.path.join(d, "ref_out")
    m_ref = run_shard(out["db"], out["las"], ref_dir, 0, 1, cfg(),
                      checkpoint_every=2)
    assert not m_ref.get("degraded")
    ref_fasta = open(shard_paths(ref_dir, 0)["fasta"]).read()

    crash_dir = os.path.join(d, "crash_out")
    # measured on this corpus/config: 45 dispatches + 11 grouped fetches
    # (= 56 device ops) per clean paged run, so op 40 lands mid-shard with
    # checkpoints already committed and reads still pending
    monkeypatch.setenv("DACCORD_FAULT", "crash:40")
    with pytest.raises(InjectedCrash):
        run_shard(out["db"], out["las"], crash_dir, 0, 1, cfg(),
                  checkpoint_every=2)
    paths = shard_paths(crash_dir, 0)
    assert os.path.exists(paths["progress"])
    assert not os.path.exists(paths["manifest"])
    monkeypatch.delenv("DACCORD_FAULT")
    run_shard(out["db"], out["las"], crash_dir, 0, 1, cfg(),
              checkpoint_every=2)
    assert open(paths["fasta"]).read() == ref_fasta
