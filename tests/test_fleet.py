"""Shard fleet orchestrator: supervised multi-shard runs (ISSUE 3).

The crash matrix runs on CPU with real ``daccord-shard`` worker subprocesses
on the native backend (no XLA compiles, ~seconds per tiny shard): injected
``worker_crash`` / ``worker_hang`` / ``lease_stall`` faults must not change a
single output byte, a poison shard must quarantine without blocking the
fleet, and the merge gate must refuse anything degraded or inconsistent.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

from daccord_tpu.parallel import fleet as fleet_mod
from daccord_tpu.parallel.fleet import FleetConfig, flag_stragglers, run_fleet
from daccord_tpu.parallel.launch import (
    MergeGateError,
    load_shard_manifest,
    merge_shards,
    run_shard,
    shard_paths,
)
from daccord_tpu.runtime.faults import FaultPlan, non_fleet_spec
from daccord_tpu.runtime.pipeline import PipelineConfig
from daccord_tpu.sim import SimConfig, make_dataset


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("fleetdata"))
    return make_dataset(d, SimConfig(genome_len=1200, coverage=10,
                                     read_len_mean=400, min_overlap=150,
                                     seed=7), name="fl")


def _fleet_cfg(tmp_path, nshards=4, **kw):
    kw.setdefault("workers", 2)
    kw.setdefault("backend", "native")
    kw.setdefault("checkpoint_every", 2)
    kw.setdefault("backoff_base_s", 0.05)
    kw.setdefault("backoff_cap_s", 0.5)
    kw.setdefault("speculate_min_runtime_s", 300.0)  # never in these tests
    return FleetConfig(nshards=nshards,
                       events_path=os.path.join(str(tmp_path), "fleet.events.jsonl"),
                       **kw)


def _events(cfg):
    with open(cfg.events_path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


def _lint(cfg):
    from daccord_tpu.tools.eventcheck import validate_events

    assert validate_events(cfg.events_path, strict=True) == []


# ---------------------------------------------------------------------------
# acceptance matrix: crash + hang + lease stall in ONE unattended run
# ---------------------------------------------------------------------------

def test_fleet_fault_matrix_byte_parity(dataset, tmp_path):
    """A 4-shard fleet with injected worker_crash, worker_hang and
    lease_stall completes unattended and merges byte-identically to a
    fault-free fleet run; the event sidecar lints clean and records the
    takeover and the retries."""
    ref_dir = str(tmp_path / "ref")
    cfg_ref = _fleet_cfg(ref_dir)
    m_ref = run_fleet(dataset["db"], dataset["las"], ref_dir, cfg_ref,
                      faults=None)
    assert m_ref["done"] == [0, 1, 2, 3] and not m_ref["poison"]
    ref_fasta = str(tmp_path / "ref.fasta")
    merge_shards(ref_dir, 4, ref_fasta)

    flt_dir = str(tmp_path / "faulted")
    cfg = _fleet_cfg(flt_dir, stall_timeout_s=10.0, max_attempts=6)
    plan = FaultPlan.parse("worker_crash:1,worker_hang:2,lease_stall:1")
    m = run_fleet(dataset["db"], dataset["las"], flt_dir, cfg, faults=plan)
    assert m["done"] == [0, 1, 2, 3] and not m["poison"], m
    out_fasta = str(tmp_path / "faulted.fasta")
    merge_shards(flt_dir, 4, out_fasta)
    assert open(out_fasta).read() == open(ref_fasta).read()

    _lint(cfg)
    ev = _events(cfg)
    kinds = {e["kind"] for e in ev if e["event"] == "fleet.fault"}
    assert kinds == {"worker_crash", "worker_hang", "lease_stall"}
    assert any(e["event"] == "fleet.takeover" for e in ev)  # stalled lease
    retries = [e for e in ev if e["event"] == "fleet.retry"]
    assert {e["reason"] for e in retries} >= {"hang"}  # hung worker requeued
    assert sum(e["event"] == "fleet.done" for e in ev) == 4
    assert any(e["event"] == "fleet.heartbeat" for e in ev)


def test_fleet_idempotent_rerun(dataset, tmp_path):
    """Re-running a finished fleet spawns no workers (every manifest is
    trusted via the validating short-circuit)."""
    d = str(tmp_path / "once")
    cfg = _fleet_cfg(d, nshards=2)
    m = run_fleet(dataset["db"], dataset["las"], d, cfg, faults=None)
    assert m["done"] == [0, 1]
    cfg2 = _fleet_cfg(d, nshards=2)
    cfg2.events_path = os.path.join(d, "rerun.events.jsonl")
    m2 = run_fleet(dataset["db"], dataset["las"], d, cfg2, faults=None)
    assert m2["done"] == [0, 1]
    assert all(a == 0 for a in m2["attempts"].values())
    with open(cfg2.events_path) as fh:
        assert not any(json.loads(ln)["event"] == "fleet.spawn" for ln in fh)


# ---------------------------------------------------------------------------
# lease protocol — the claim/renew/takeover/release units moved to
# tests/test_lease.py alongside the extracted utils/lease.py (ISSUE 15);
# what stays here is the fleet-level integration behavior.
# ---------------------------------------------------------------------------

def test_lease_takeover_by_second_orchestrator(dataset, tmp_path):
    """Orchestrator A (a real second OS process) claims shard 0 and dies
    without heartbeating — the wedged-host scenario lease_stall injects.
    Orchestrator B takes the stale lease over and completes the fleet."""
    d = str(tmp_path / "shards")
    os.makedirs(d)
    wedged = f"""
import sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from daccord_tpu.parallel import fleet
ok, _ = fleet.claim_lease({d!r}, 0, "orchA", ttl_s=60.0)
assert ok
fleet.backdate_lease({d!r}, 0, age_s=120.0)  # died right after claiming
"""
    subprocess.run([sys.executable, "-c", wedged], check=True)
    assert os.path.exists(fleet_mod.lease_path(d, 0))

    cfg = _fleet_cfg(d, nshards=2, host="orchB", lease_ttl_s=60.0)
    m = run_fleet(dataset["db"], dataset["las"], d, cfg, faults=None)
    assert m["done"] == [0, 1]
    takeovers = [e for e in _events(cfg) if e["event"] == "fleet.takeover"]
    assert takeovers and takeovers[0]["prev_host"] == "orchA"
    assert takeovers[0]["shard"] == 0
    _lint(cfg)


# ---------------------------------------------------------------------------
# poison-shard quarantine
# ---------------------------------------------------------------------------

def test_poison_shard_quarantined_fleet_continues(dataset, tmp_path):
    """A shard whose input kills every worker (corrupt LAS under strict
    ingest) is declared poison after K consecutive failures — with the
    structured ingest report in its stderr tail — while the other shards
    complete; the merge gate then refuses without --allow-degraded and
    merges exactly the survivors with it."""
    from daccord_tpu.formats.las import shard_ranges
    from daccord_tpu.runtime.faults import (
        _las_record_offsets,
        _read_all,
        corrupt_las_bitflip,
    )

    las = str(tmp_path / "poison.las")
    shutil.copy(dataset["las"], las)
    offs = _las_record_offsets(_read_all(las))
    start, end = shard_ranges(las, 4)[2]
    rec = next(i for i, o in enumerate(offs, start=1) if start <= o < end)
    corrupt_las_bitflip(las, rec)

    d = str(tmp_path / "shards")
    cfg = _fleet_cfg(d, poison_after=2, ingest_policy="strict")
    m = run_fleet(dataset["db"], las, d, cfg, faults=None)
    assert m["done"] == [0, 1, 3]
    assert [p["shard"] for p in m["poison"]] == [2]
    p = m["poison"][0]
    assert p["attempts"] == 2 and "consecutive" in p["reason"]
    assert "bad_coords" in p["stderr_tail"]  # the ingest report is preserved
    # the durable fleet manifest says the same thing
    disk = json.load(open(os.path.join(d, "fleet.json")))
    assert [q["shard"] for q in disk["poison"]] == [2]
    _lint(cfg)
    assert any(e["event"] == "fleet.poison" for e in _events(cfg))

    out = str(tmp_path / "merged.fasta")
    with pytest.raises(MergeGateError, match="missing shard output"):
        merge_shards(d, 4, out)
    assert not os.path.exists(out)
    merge_shards(d, 4, out, allow_degraded=True)
    survivors = "".join(open(shard_paths(d, s)["fasta"]).read()
                        for s in (0, 1, 3))
    assert open(out).read() == survivors


# ---------------------------------------------------------------------------
# merge gate
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def two_shards(dataset, tmp_path_factory):
    """Two in-process shard runs (native engine) used by the gate tests —
    each test copies the directory before tampering."""
    d = str(tmp_path_factory.mktemp("gate"))
    cfg = PipelineConfig(native_solver=True, batch_size=128)
    for s in (0, 1):
        run_shard(dataset["db"], dataset["las"], d, s, 2, cfg)
    return d


def _copy(two_shards, tmp_path):
    d = str(tmp_path / "shards")
    shutil.copytree(two_shards, d)
    return d


def test_merge_gate_ok_and_durable(two_shards, tmp_path):
    out = str(tmp_path / "all.fasta")
    n = merge_shards(two_shards, 2, out)
    concat = "".join(open(shard_paths(two_shards, s)["fasta"]).read()
                     for s in (0, 1))
    assert open(out).read() == concat
    assert n == concat.count(">")
    # no tmp litter from the durable commit
    assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []


def test_merge_gate_refuses_degraded_shard(two_shards, tmp_path):
    d = _copy(two_shards, tmp_path)
    mpath = shard_paths(d, 1)["manifest"]
    m = json.load(open(mpath))
    m["degraded"], m["fallback_reason"] = True, "device_lost"
    json.dump(m, open(mpath, "wt"))
    with pytest.raises(MergeGateError, match="degraded"):
        merge_shards(d, 2, str(tmp_path / "out.fasta"))
    assert not os.path.exists(tmp_path / "out.fasta")
    # explicit override merges it (the output is still byte-exact)
    merge_shards(d, 2, str(tmp_path / "out.fasta"), allow_degraded=True)
    assert os.path.exists(tmp_path / "out.fasta")


def test_merge_gate_catches_truncated_fasta(two_shards, tmp_path):
    d = _copy(two_shards, tmp_path)
    fasta = shard_paths(d, 0)["fasta"]
    with open(fasta, "r+") as fh:
        fh.truncate(os.path.getsize(fasta) - 10)
    # truncation is corruption, NOT a skippable degraded state
    for allow in (False, True):
        with pytest.raises(MergeGateError, match="truncated"):
            merge_shards(d, 2, str(tmp_path / "out.fasta"),
                         allow_degraded=allow)


def test_merge_gate_refuses_digest_mismatch(two_shards, tmp_path):
    """Silent corruption (ISSUE 20): same byte COUNT, different bytes — the
    size/truncation gates pass, only the content digest can refuse it."""
    d = _copy(two_shards, tmp_path)
    fasta = shard_paths(d, 1)["fasta"]
    raw = open(fasta, "rb").read()
    # flip one consensus base on a sequence line (never a header) — exactly
    # what a lying chip's output looks like after a clean commit
    seq_at = raw.index(b"\n") + 1
    flip = b"C" if raw[seq_at:seq_at + 1] != b"C" else b"G"
    with open(fasta, "r+b") as fh:
        fh.seek(seq_at)
        fh.write(flip)
    assert os.path.getsize(fasta) == len(raw)
    out = str(tmp_path / "out.fasta")
    with pytest.raises(MergeGateError, match="digest"):
        merge_shards(d, 2, out)
    assert not os.path.exists(out)
    # explicit override merges the bytes on disk (the operator's call)
    merge_shards(d, 2, out, allow_degraded=True)
    assert os.path.exists(out)


def test_merge_gate_cross_checks_read_counts(two_shards, tmp_path):
    d = _copy(two_shards, tmp_path)
    fasta = shard_paths(d, 0)["fasta"]
    with open(fasta, "at") as fh:
        fh.write(">read99999/0\nACGT\n")
    mpath = shard_paths(d, 0)["manifest"]
    m = json.load(open(mpath))
    from daccord_tpu.utils.obs import sha256_file

    m["fasta_bytes"] = os.path.getsize(fasta)  # size agrees; counts cannot
    m["fasta_sha256"] = sha256_file(fasta)     # digest too (independent gate)
    json.dump(m, open(mpath, "wt"))
    out = str(tmp_path / "out.fasta")
    with pytest.raises(MergeGateError, match="fragments|reads"):
        merge_shards(d, 2, out)
    assert not os.path.exists(out)  # aborted before the durable rename


def test_daccord_audit_offline_chain(two_shards, tmp_path, capsys):
    """daccord-audit (ISSUE 20): the cold half of the integrity chain —
    exit 0 on a clean tree, exit 1 naming the corrupted link, exit 2 when
    there is nothing auditable."""
    from daccord_tpu.tools.audit import audit_main

    assert audit_main([two_shards]) == 0
    d = _copy(two_shards, tmp_path)
    fasta = shard_paths(d, 0)["fasta"]
    raw = open(fasta, "rb").read()
    seq_at = raw.index(b"\n") + 1
    with open(fasta, "r+b") as fh:
        fh.seek(seq_at)
        fh.write(b"C" if raw[seq_at:seq_at + 1] != b"C" else b"G")
    assert audit_main([d, "--json"]) == 1
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    bad = [c for c in rep["checks"] if not c["ok"]]
    assert bad and "shard 0" in bad[0]["check"]
    empty = str(tmp_path / "nothing")
    os.makedirs(empty)
    assert audit_main([empty]) == 2


def test_merge_gate_refuses_wrong_split(two_shards, tmp_path):
    with pytest.raises(MergeGateError, match="missing shard"):
        merge_shards(two_shards, 3, str(tmp_path / "out.fasta"))


# ---------------------------------------------------------------------------
# satellite: run_shard stale-manifest short-circuit
# ---------------------------------------------------------------------------

def test_run_shard_recomputes_when_fasta_missing(dataset, tmp_path):
    d = str(tmp_path)
    cfg = PipelineConfig(native_solver=True, batch_size=128)
    m = run_shard(dataset["db"], dataset["las"], d, 0, 2, cfg)
    fasta = shard_paths(d, 0)["fasta"]
    ref = open(fasta).read()
    assert m["fasta_bytes"] == os.path.getsize(fasta)

    os.remove(fasta)
    got, why = load_shard_manifest(d, 0)
    assert got is None and "missing" in why
    m2 = run_shard(dataset["db"], dataset["las"], d, 0, 2, cfg)
    assert open(fasta).read() == ref and m2["reads"] == m["reads"]

    with open(fasta, "r+") as fh:  # truncation must also void the manifest
        fh.truncate(10)
    got, why = load_shard_manifest(d, 0)
    assert got is None and "truncated" in why
    m3 = run_shard(dataset["db"], dataset["las"], d, 0, 2, cfg)
    assert open(fasta).read() == ref and m3["reads"] == m["reads"]

    # intact manifest still short-circuits (idempotence preserved)
    m4 = run_shard(dataset["db"], dataset["las"], d, 0, 2, cfg)
    assert m4 == m3


def test_run_shard_refuses_short_fasta_resume(dataset, tmp_path):
    """A progress manifest claiming more durable FASTA bytes than the file
    holds (torn/damaged FASTA) must trigger a fresh recompute — resuming
    would zero-fill the hole via truncate() and splice output onto NULs."""
    cfg = PipelineConfig(native_solver=True, batch_size=128)
    ref_dir = str(tmp_path / "ref")
    m_ref = run_shard(dataset["db"], dataset["las"], ref_dir, 0, 1, cfg,
                      checkpoint_every=2)
    ref = open(shard_paths(ref_dir, 0)["fasta"]).read()

    d = str(tmp_path / "torn")
    os.makedirs(d)
    paths = shard_paths(d, 0)
    with open(paths["fasta"], "wt") as fh:
        fh.write(ref[:40])  # 40 durable bytes on disk...
    from daccord_tpu.formats.las import shard_ranges

    start, end = shard_ranges(dataset["las"], 1)[0]
    json.dump({"emitted": 2, "fasta_bytes": 4096,  # ...checkpoint claims 4096
               "counters": {"reads": 2, "windows": 0, "solved": 0,
                            "bases_out": 0, "fragments": 2, "wall_s": 0.0},
               "profile": [0.05, 0.05, 0.05], "byte_range": [start, end]},
              open(paths["progress"], "wt"))
    m = run_shard(dataset["db"], dataset["las"], d, 0, 1, cfg,
                  checkpoint_every=2)
    got = open(paths["fasta"]).read()
    assert "\x00" not in got
    assert got == ref and m["reads"] == m_ref["reads"]
    assert "resumed_at_read" not in m  # fresh run, not a resume


# ---------------------------------------------------------------------------
# supervision-loop units (stub workers — no subprocesses)
# ---------------------------------------------------------------------------

class _StubProc:
    def __init__(self):
        self.killed = False

    def poll(self):
        return -9 if self.killed else None

    def kill(self):
        self.killed = True


def _stub_fleet(dataset, outdir, **kw):
    cfg = _fleet_cfg(outdir, nshards=1, **kw)
    cfg.events_path = None
    return fleet_mod.Fleet(dataset["db"], dataset["las"], str(outdir), cfg)


def test_watchdog_not_muted_by_stale_manifest(dataset, tmp_path):
    """A manifest predating the current attempt (the stale artifact this
    attempt exists to recompute) must not suppress hang detection."""
    import time

    f = _stub_fleet(dataset, tmp_path, stall_timeout_s=5.0)
    st = f.shards[0]
    st.status, st.proc, st.spawn_t = "running", _StubProc(), time.time() - 60
    mpath = shard_paths(str(tmp_path), 0)["manifest"]
    json.dump({"shard": 0}, open(mpath, "wt"))
    old = st.spawn_t - 100
    os.utime(mpath, (old, old))  # stale: committed long before this spawn
    f._watchdog(time.time())
    assert st.proc.killed and st.kill_reason == "hang"

    # a manifest committed during the attempt (worker finishing) DOES mute it
    st2_proc = _StubProc()
    st.proc, st.kill_reason, st.spawn_t = st2_proc, None, time.time() - 60
    os.utime(mpath, None)
    f._watchdog(time.time())
    assert not st2_proc.killed


def test_heartbeat_detects_ownership_loss(dataset, tmp_path):
    """If another orchestrator took the shard over (our lease went stale
    during a host pause), the heartbeat must kill our worker and demote the
    shard to foreign instead of renewing the taker's lease."""
    f = _stub_fleet(dataset, tmp_path)
    st = f.shards[0]
    st.status, st.proc, st.last_beat = "running", _StubProc(), 0.0
    ok, _ = fleet_mod.claim_lease(str(tmp_path), 0, "taker-host", ttl_s=60.0)
    assert ok  # the taker's lease, not ours
    import time

    f._heartbeat(time.time())
    assert st.proc.killed and st.kill_reason == "ownership_lost"
    f._reap()
    assert st.status == "foreign"
    # the taker's lease must survive our exit paths
    fleet_mod.release_lease(str(tmp_path), 0, host=f.host)
    assert fleet_mod.read_lease(str(tmp_path), 0)["host"] == "taker-host"


# ---------------------------------------------------------------------------
# small units
# ---------------------------------------------------------------------------

def test_flag_stragglers():
    assert flag_stragglers({}, 4.0) == []
    assert flag_stragglers({0: 1.0}, 4.0) == []            # nothing to compare
    assert flag_stragglers({0: 1.0, 1: 0.9, 2: 0.1}, 4.0) == [2]
    assert flag_stragglers({0: 1.0, 1: 0.9, 2: 0.5}, 4.0) == []
    assert flag_stragglers({0: 0.0, 1: 0.0}, 4.0) == []    # startup noise
    assert flag_stragglers({0: 1.0, 1: 0.0}, 0.0) == []    # disabled


def test_non_fleet_spec_strips_only_fleet_kinds():
    assert non_fleet_spec("worker_crash:1,las_bitflip:3") == "las_bitflip:3"
    assert non_fleet_spec("worker_hang:2,lease_stall") == ""
    assert non_fleet_spec("device_lost:2,crash:9") == "device_lost:2,crash:9"
    assert non_fleet_spec(None) == ""


def test_jsonl_logger_context_manager(tmp_path):
    from daccord_tpu.utils.obs import JsonlLogger

    p = str(tmp_path / "ev.jsonl")
    with JsonlLogger(p) as log:
        log.log("fleet.fault", kind="worker_hang", shard=1)
        fh = log._fh
    assert fh.closed
    rec = json.loads(open(p).read())
    assert rec["event"] == "fleet.fault" and rec["shard"] == 1
    with JsonlLogger(None) as log:  # disabled logger is ctx-safe too
        log.log("noop")
