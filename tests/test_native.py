"""Native C++ host path: bit-parity with the Python oracle path."""

import numpy as np
import pytest

from daccord_tpu.formats import LasFile, read_db
from daccord_tpu.kernels import BatchShape, tensorize_windows
from daccord_tpu.oracle import cut_windows, refine_overlap
from daccord_tpu.sim import SimConfig, make_dataset

native = pytest.importorskip("daccord_tpu.native")
if not native.available():
    pytest.skip("native library unavailable", allow_module_level=True)

from daccord_tpu.native.api import ColumnarLas, process_pile_native


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("native"))
    cfg = SimConfig(genome_len=3000, coverage=16, read_len_mean=800, seed=19)
    return make_dataset(d, cfg, name="n"), d


def test_decode_reads_batch_bit_parity(dataset):
    """Native 2-bit batch decode == per-read Python unpack, including
    non-multiple-of-4 lengths."""
    out, d = dataset
    db = read_db(out["db"])
    ids = list(range(db.nreads)) + [0, db.nreads - 1]
    got = db.read_bases_batch(ids)
    for i, g in zip(ids, got):
        np.testing.assert_array_equal(g, db.read_bases(i))


def test_columnar_las_matches_python_reader(dataset):
    out, d = dataset
    col = ColumnarLas(out["las"])
    las = LasFile(out["las"])
    ovls = list(las)
    assert col.novl == len(ovls)
    assert col.tspace == las.tspace
    for i in (0, 1, len(ovls) // 2, len(ovls) - 1):
        o = ovls[i]
        assert (col.aread[i], col.bread[i]) == (o.aread, o.bread)
        assert (col.abpos[i], col.aepos[i]) == (o.abpos, o.aepos)
        assert (col.bbpos[i], col.bepos[i]) == (o.bbpos, o.bepos)
        assert bool(col.comp[i]) == o.is_comp
        tr = col.trace_flat[col.trace_off[i] : col.trace_off[i + 1]].reshape(-1, 2)
        np.testing.assert_array_equal(tr, o.trace)


def test_columnar_byte_range(dataset):
    out, d = dataset
    from daccord_tpu.formats.las import shard_ranges

    r = shard_ranges(out["las"], 2)
    c0 = ColumnarLas(out["las"], r[0][0], r[0][1])
    c1 = ColumnarLas(out["las"], r[1][0], r[1][1])
    full = ColumnarLas(out["las"])
    assert c0.novl + c1.novl == full.novl
    np.testing.assert_array_equal(np.concatenate([c0.aread, c1.aread]), full.aread)


def test_process_pile_bit_parity(dataset):
    out, d = dataset
    db = read_db(out["db"])
    col = ColumnarLas(out["las"])
    las = LasFile(out["las"])
    piles = dict(las.iter_piles())
    shape = BatchShape(depth=32, seg_len=64, wlen=40)
    checked = 0
    for aread, s, e in list(col.piles())[:6]:
        a = db.read_bases(aread)
        b_reads = [db.read_bases(int(col.bread[i])) for i in range(s, e)]
        seqs, lens, nsegs = process_pile_native(a, col, s, e, b_reads, 40, 10, 32, 64)
        refined = [refine_overlap(o, a, db.read_bases(o.bread), col.tspace) for o in piles[aread]]
        windows = cut_windows(a, refined)
        batch = tensorize_windows([(aread, ws) for ws in windows], shape)
        np.testing.assert_array_equal(batch.seqs, seqs)
        np.testing.assert_array_equal(batch.lens, lens)
        np.testing.assert_array_equal(batch.nsegs, nsegs)
        checked += 1
    assert checked == 6


def test_process_pile_with_order(dataset):
    """Quality-ranked order must match reordering the Python pile."""
    out, d = dataset
    db = read_db(out["db"])
    col = ColumnarLas(out["las"])
    las = LasFile(out["las"])
    piles = dict(las.iter_piles())
    shape = BatchShape(depth=32, seg_len=64, wlen=40)
    aread, s, e = next(iter(col.piles()))
    a = db.read_bases(aread)
    span = np.maximum(col.aepos[s:e] - col.abpos[s:e], 1)
    order = np.argsort(col.diffs[s:e] / span, kind="stable")
    b_reads = [db.read_bases(int(col.bread[s + int(j)])) for j in order]
    seqs, lens, nsegs = process_pile_native(a, col, s, e, b_reads, 40, 10, 32, 64, order=order)

    pile = sorted(piles[aread], key=lambda o: o.diffs / max(o.aepos - o.abpos, 1))
    refined = [refine_overlap(o, a, db.read_bases(o.bread), col.tspace) for o in pile]
    windows = cut_windows(a, refined)
    batch = tensorize_windows([(aread, ws) for ws in windows], shape)
    np.testing.assert_array_equal(batch.seqs, seqs)
    np.testing.assert_array_equal(batch.lens, lens)


@pytest.mark.slow   # full-pipeline run -> ladder-shape XLA compiles (~2 min)
def test_wide_tspace_native_pipeline_parity(tmp_path):
    """tspace > 125 (uint16 trace points on disk) through the FULL pipeline:
    the native columnar loader's 2-byte trace branch and the banded
    realignment (band hint = per-tile diffs) produce output byte-identical
    to the pure-Python path."""
    from daccord_tpu.runtime.pipeline import PipelineConfig, correct_to_fasta

    cfg = SimConfig(genome_len=3000, coverage=14, read_len_mean=800,
                    tspace=200, seed=29)
    out = make_dataset(str(tmp_path), cfg, name="w")
    assert LasFile(out["las"]).tspace == 200

    fa_native = str(tmp_path / "native.fasta")
    fa_python = str(tmp_path / "python.fasta")
    st_n = correct_to_fasta(out["db"], out["las"], fa_native,
                            PipelineConfig(use_native=True))
    st_p = correct_to_fasta(out["db"], out["las"], fa_python,
                            PipelineConfig(use_native=False))
    assert st_n.native_host and not st_p.native_host
    assert open(fa_native).read() == open(fa_python).read()
    assert st_n.n_solved == st_p.n_solved > 0


def test_native_consensus_oracle_parity(dataset):
    """solve_windows (C++ full-graph tier ladder) vs the Python oracle
    solve_window, window by window on identical truncated segments: same
    solved set, same tier, identical consensus bases. Float accumulation
    differs from BLAS in the last ulp, so err agrees to 1e-5 and parity is
    asserted on the sequences (dazz_native.cpp solve_windows docstring)."""
    from dataclasses import replace

    from daccord_tpu.native.api import solve_windows_native
    from daccord_tpu.oracle import estimate_profile_two_pass
    from daccord_tpu.oracle.consensus import (ConsensusConfig,
                                              make_offset_likely)
    from daccord_tpu.oracle.dbg import DBGParams, window_consensus

    (paths, d) = dataset
    db = read_db(paths["db"])
    las = LasFile(paths["las"])
    ccfg = ConsensusConfig()
    windows = []
    for aread, pile in las.iter_piles():
        a = db.read_bases(aread)
        refined = [refine_overlap(o, a, db.read_bases(o.bread), las.tspace)
                   for o in pile]
        windows.extend(cut_windows(a, refined, w=ccfg.w, adv=ccfg.adv))
        if len(windows) >= 160:
            break
    prof = estimate_profile_two_pass(
        refined, windows[:40], ccfg, sample=12)
    ols = make_offset_likely(prof, ccfg)
    shape = BatchShape(depth=24, seg_len=64, wlen=ccfg.w)
    batch = tensorize_windows([(0, ws) for ws in windows], shape)

    out = solve_windows_native(batch, ols, ccfg)

    n_solved = mism = 0
    for i, ws in enumerate(windows):
        segs = [np.asarray(s[: shape.seg_len], dtype=np.int8)
                for s in ws.segments[: shape.depth]]
        o_seq, o_tier = None, -1
        if len(segs) >= ccfg.dbg.min_depth:
            for ti, (k, mc, emc) in enumerate(ccfg.tiers):
                p = DBGParams(**{**ccfg.dbg.__dict__, "k": k,
                                 "min_count": mc, "edge_min_count": emc})
                r = window_consensus(segs, ols[k], p, wlen=ccfg.w)
                if r.seq is not None:
                    o_seq, o_tier = r.seq, ti
                    break
        n_seq = (out["cons"][i][: out["cons_len"][i]]
                 if out["solved"][i] else None)
        same = (o_seq is None) == (n_seq is None) and (
            o_seq is None or (np.array_equal(o_seq, n_seq)
                              and o_tier == out["tier"][i]))
        if not same:
            mism += 1
        if o_seq is not None:
            n_solved += 1
    assert n_solved > 100, n_solved
    # sequential-f32 vs BLAS weight sums can flip exact score ties; allow a
    # whisker, require essentially-total agreement
    assert mism <= max(1, len(windows) // 100), (mism, len(windows))


def test_native_consensus_topm_cap(dataset):
    """Native top-M compaction: a huge cap is bitwise the full graph; a tiny
    cap flags m_ovf on truncated windows and changes only flagged windows."""
    from daccord_tpu.native.api import solve_windows_native
    from daccord_tpu.oracle import estimate_profile_two_pass
    from daccord_tpu.oracle.consensus import (ConsensusConfig,
                                              make_offset_likely)

    (paths, d) = dataset
    db = read_db(paths["db"])
    las = LasFile(paths["las"])
    ccfg = ConsensusConfig()
    windows = []
    for aread, pile in las.iter_piles():
        a = db.read_bases(aread)
        refined = [refine_overlap(o, a, db.read_bases(o.bread), las.tspace)
                   for o in pile]
        windows.extend(cut_windows(a, refined, w=ccfg.w, adv=ccfg.adv))
        if len(windows) >= 120:
            break
    prof = estimate_profile_two_pass(refined, windows[:40], ccfg, sample=12)
    ols = make_offset_likely(prof, ccfg)
    shape = BatchShape(depth=24, seg_len=64, wlen=ccfg.w)
    batch = tensorize_windows([(0, ws) for ws in windows], shape)

    full = solve_windows_native(batch, ols, ccfg)
    huge = solve_windows_native(batch, ols, ccfg, max_kmers=100_000,
                                rescue_max_kmers=100_000)
    for key in ("cons", "cons_len", "solved", "tier"):
        np.testing.assert_array_equal(full[key], huge[key], key)
    assert not huge["m_ovf"].any()

    tiny = solve_windows_native(batch, ols, ccfg, max_kmers=16)
    assert tiny["m_ovf"].sum() > 10, int(tiny["m_ovf"].sum())
    # windows the cap never touched must match the full graph exactly
    clean = ~tiny["m_ovf"]
    np.testing.assert_array_equal(tiny["cons"][clean], full["cons"][clean])
    np.testing.assert_array_equal(tiny["cons_len"][clean],
                                  full["cons_len"][clean])
