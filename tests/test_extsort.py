"""External-memory LAS sort + symmetric filter (SURVEY.md §2.2 LAS row:
the reference's LAsort/LAmerge are block-memory external sorts)."""

import os

import numpy as np
import pytest

from daccord_tpu.formats import LasFile, read_db
from daccord_tpu.formats.extsort import filter_symmetric_external, sort_las_external
from daccord_tpu.formats.las import write_las
from daccord_tpu.sim import SimConfig, make_dataset
from daccord_tpu.tools import lastools


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("ext"))
    cfg = SimConfig(genome_len=4000, coverage=14, read_len_mean=700, seed=29)
    return make_dataset(d, cfg, name="x"), d


def test_external_sort_matches_inmemory(dataset):
    out, d = dataset
    las = LasFile(out["las"])
    assert las.novl > 200   # enough records to force many runs below

    # scramble so the sort has real work
    rng = np.random.default_rng(5)
    ovls = list(las)
    perm = rng.permutation(len(ovls))
    shuffled = os.path.join(d, "shuf.las")
    write_las(shuffled, las.tspace, [ovls[i] for i in perm])

    ref = os.path.join(d, "sorted_mem.las")
    write_las(ref, las.tspace,
              sorted(LasFile(shuffled), key=lambda o: (o.aread, o.bread, o.abpos)))

    ext = os.path.join(d, "sorted_ext.las")
    # mem_records=50 on >200 records: >=5 on-disk runs + k-way merge
    n = sort_las_external(shuffled, ext, mem_records=50)
    assert n == las.novl
    assert open(ext, "rb").read() == open(ref, "rb").read()


def test_external_sort_empty(tmp_path):
    empty = str(tmp_path / "empty.las")
    write_las(empty, 100, [])
    out = str(tmp_path / "sorted.las")
    assert sort_las_external(empty, out, mem_records=10) == 0
    assert LasFile(out).novl == 0


def test_filter_symmetric_external_matches_inmemory(dataset):
    out, d = dataset
    db = read_db(out["db"], load_bases=False)
    las = LasFile(out["las"])

    # break symmetry: drop a slice of records so some mirrors go missing
    ovls = list(las)
    asym = os.path.join(d, "asym.las")
    write_las(asym, las.tspace, [o for i, o in enumerate(ovls) if i % 7 != 3])

    ref = os.path.join(d, "sym_mem.las")
    n_mem = lastools.filter_symmetric(asym, ref, db=db)

    ext = os.path.join(d, "sym_ext.las")
    # mem_records=64 forces many hash partitions; batch=50 exercises the
    # multi-batch emit path
    n_ext = filter_symmetric_external(asym, ext, db, mem_records=64, batch=50)
    assert n_ext == n_mem > 0
    assert open(ext, "rb").read() == open(ref, "rb").read()


def test_external_sort_multilevel_merge(dataset):
    """>64 runs trigger the multi-level merge (fd-limit cap); output stays
    byte-identical to the in-memory sort."""
    out, d = dataset
    las = LasFile(out["las"])
    n_rec = las.novl
    mem = max(1, n_rec // 70)   # ~70 runs > FANIN=64
    ref = os.path.join(d, "ml_ref.las")
    write_las(ref, las.tspace,
              sorted(las, key=lambda o: (o.aread, o.bread, o.abpos)))
    ext = os.path.join(d, "ml_ext.las")
    assert sort_las_external(out["las"], ext, mem_records=mem) == n_rec
    assert open(ext, "rb").read() == open(ref, "rb").read()
