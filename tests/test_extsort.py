"""External-memory LAS sort + symmetric filter (SURVEY.md §2.2 LAS row:
the reference's LAsort/LAmerge are block-memory external sorts)."""

import os

import numpy as np
import pytest

from daccord_tpu.formats import LasFile, read_db
from daccord_tpu.formats.extsort import filter_symmetric_external, sort_las_external
from daccord_tpu.formats.las import write_las
from daccord_tpu.sim import SimConfig, make_dataset
from daccord_tpu.tools import lastools


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("ext"))
    cfg = SimConfig(genome_len=4000, coverage=14, read_len_mean=700, seed=29)
    return make_dataset(d, cfg, name="x"), d


def test_external_sort_matches_inmemory(dataset):
    out, d = dataset
    las = LasFile(out["las"])
    assert las.novl > 200   # enough records to force many runs below

    # scramble so the sort has real work
    rng = np.random.default_rng(5)
    ovls = list(las)
    perm = rng.permutation(len(ovls))
    shuffled = os.path.join(d, "shuf.las")
    write_las(shuffled, las.tspace, [ovls[i] for i in perm])

    ref = os.path.join(d, "sorted_mem.las")
    write_las(ref, las.tspace,
              sorted(LasFile(shuffled), key=lambda o: (o.aread, o.bread, o.abpos)))

    ext = os.path.join(d, "sorted_ext.las")
    # mem_records=50 on >200 records: >=5 on-disk runs + k-way merge
    n = sort_las_external(shuffled, ext, mem_records=50)
    assert n == las.novl
    assert open(ext, "rb").read() == open(ref, "rb").read()


def test_external_sort_empty(tmp_path):
    empty = str(tmp_path / "empty.las")
    write_las(empty, 100, [])
    out = str(tmp_path / "sorted.las")
    assert sort_las_external(empty, out, mem_records=10) == 0
    assert LasFile(out).novl == 0


def test_filter_symmetric_external_matches_inmemory(dataset):
    out, d = dataset
    db = read_db(out["db"], load_bases=False)
    las = LasFile(out["las"])

    # break symmetry: drop a slice of records so some mirrors go missing
    ovls = list(las)
    asym = os.path.join(d, "asym.las")
    write_las(asym, las.tspace, [o for i, o in enumerate(ovls) if i % 7 != 3])

    ref = os.path.join(d, "sym_mem.las")
    n_mem = lastools.filter_symmetric(asym, ref, db=db)

    ext = os.path.join(d, "sym_ext.las")
    # mem_records=64 forces many hash partitions; batch=50 exercises the
    # multi-batch emit path
    n_ext = filter_symmetric_external(asym, ext, db, mem_records=64, batch=50)
    assert n_ext == n_mem > 0
    assert open(ext, "rb").read() == open(ref, "rb").read()


def test_external_sort_multilevel_merge(dataset):
    """>64 runs trigger the multi-level merge (fd-limit cap); output stays
    byte-identical to the in-memory sort."""
    out, d = dataset
    las = LasFile(out["las"])
    n_rec = las.novl
    mem = max(1, n_rec // 70)   # ~70 runs > FANIN=64
    ref = os.path.join(d, "ml_ref.las")
    write_las(ref, las.tspace,
              sorted(las, key=lambda o: (o.aread, o.bread, o.abpos)))
    ext = os.path.join(d, "ml_ext.las")
    assert sort_las_external(out["las"], ext, mem_records=mem) == n_rec
    assert open(ext, "rb").read() == open(ref, "rb").read()


def test_native_sort_matches_python(dataset):
    """The native external sort is byte-identical to the Python spec path
    at the same mem_records (multi-run and single-chunk regimes)."""
    from daccord_tpu.native import available

    if not available():
        pytest.skip("native host path unavailable")
    out, d = dataset
    las = LasFile(out["las"])
    rng = np.random.default_rng(11)
    ovls = list(las)
    perm = rng.permutation(len(ovls))
    shuffled = os.path.join(d, "nshuf.las")
    write_las(shuffled, las.tspace, [ovls[i] for i in perm])

    for mem in (50, 10_000_000):   # many runs / single-chunk fast path
        py = os.path.join(d, f"nsort_py{mem}.las")
        nat = os.path.join(d, f"nsort_nat{mem}.las")
        n1 = sort_las_external(shuffled, py, mem_records=mem, use_native=False)
        n2 = sort_las_external(shuffled, nat, mem_records=mem, use_native=True)
        assert n1 == n2 == las.novl
        assert open(py, "rb").read() == open(nat, "rb").read()


def test_native_sort_normalizes_foreign_pad_bytes(tmp_path):
    """LAS files from other producers (real DALIGNER) can carry garbage in
    the header/record struct padding; both sort paths normalize it to zeros
    so their outputs stay byte-identical."""
    from daccord_tpu.formats.las import Overlap
    from daccord_tpu.native import available

    if not available():
        pytest.skip("native host path unavailable")
    p = str(tmp_path / "pad.las")
    ovls = [Overlap(aread=a, bread=1, abpos=0, aepos=100, bbpos=0, bepos=100,
                    trace=np.asarray([[2, 100]], np.int32)) for a in (3, 1, 2)]
    write_las(p, 100, ovls)
    raw = bytearray(open(p, "rb").read())
    raw[12:16] = b"\xde\xad\xbe\xef"          # header pad
    off = 16
    for _ in ovls:
        raw[off + 36 : off + 40] = b"\xca\xfe\xba\xbe"   # record tail pad
        off += 40 + 2
    open(p, "wb").write(bytes(raw))
    py = str(tmp_path / "py.las")
    nat = str(tmp_path / "nat.las")
    sort_las_external(p, py, mem_records=2, use_native=False)
    sort_las_external(p, nat, mem_records=2, use_native=True)
    assert open(py, "rb").read() == open(nat, "rb").read()


def test_native_merge_matches_python(dataset, tmp_path):
    """las-merge's native heap merge is byte-identical to the Python
    heapq.merge path (including pad normalization on foreign inputs)."""
    from daccord_tpu.native import available

    if not available():
        pytest.skip("native host path unavailable")
    import heapq

    from daccord_tpu.native.api import las_merge_native

    out, d = dataset
    las = LasFile(out["las"])
    ovls = list(las)
    p1, p2, p3 = (str(tmp_path / f"{n}.las") for n in "abc")
    write_las(p1, las.tspace, [o for o in ovls if o.aread % 3 == 0])
    write_las(p2, las.tspace, [o for o in ovls if o.aread % 3 == 1])
    write_las(p3, las.tspace, [o for o in ovls if o.aread % 3 == 2])

    ref = str(tmp_path / "ref.las")
    write_las(ref, las.tspace,
              heapq.merge(*(iter(LasFile(p)) for p in (p1, p2, p3)),
                          key=lambda o: (o.aread, o.bread, o.abpos)))
    nat = str(tmp_path / "nat.las")
    n = las_merge_native([p1, p2, p3], nat, las.tspace)
    assert n == las.novl
    assert open(nat, "rb").read() == open(ref, "rb").read()


def test_native_sort_wide_tspace_parity(tmp_path):
    """tspace > 125 (2-byte trace values on disk): the native tsize=2 read
    path must stay byte-identical to the Python path."""
    from daccord_tpu.formats.las import Overlap
    from daccord_tpu.native import available

    if not available():
        pytest.skip("native host path unavailable")
    rng = np.random.default_rng(13)
    ovls = [Overlap(aread=int(rng.integers(0, 50)), bread=int(rng.integers(0, 50)),
                    abpos=0, aepos=300, bbpos=0, bepos=300,
                    trace=np.asarray([[int(rng.integers(0, 400)), 150],
                                      [int(rng.integers(0, 400)), 150]], np.int32))
            for _ in range(200)]
    p = str(tmp_path / "wide.las")
    write_las(p, 150, ovls)   # tspace 150 -> uint16 traces
    py = str(tmp_path / "wide_py.las")
    nat = str(tmp_path / "wide_nat.las")
    n1 = sort_las_external(p, py, mem_records=50, use_native=False)
    n2 = sort_las_external(p, nat, mem_records=50, use_native=True)
    assert n1 == n2 == 200
    assert open(py, "rb").read() == open(nat, "rb").read()


def test_native_merge_rejects_truncated_input(dataset, tmp_path):
    """A foreign LAS truncated mid-record must fail the native merge loudly
    (the Python path raises on the same input); silently dropping the tail
    would hand consensus an incomplete overlap set."""
    from daccord_tpu.native import available
    from daccord_tpu.native.api import las_merge_native

    if not available():
        pytest.skip("native host path unavailable")
    out, d = dataset
    las = LasFile(out["las"])
    good = str(tmp_path / "good.las")
    write_las(good, las.tspace, list(las)[:20])
    raw = open(good, "rb").read()
    bad = str(tmp_path / "bad.las")
    open(bad, "wb").write(raw[:-7])   # chop mid-trace
    with pytest.raises(IOError):
        las_merge_native([bad], str(tmp_path / "m.las"), las.tspace)
