"""Device supervisor: fault injection, state machine, failover parity.

The fast-tier matrix runs every injected fault kind on CPU with no XLA
ladder compiles: the state machine is unit-tested against a stub engine, and
the end-to-end arms drive the real pipeline with the native C++ solver
(byte-parity is then exact by construction — the degraded engine IS the
primary's engine). The JAX-ladder end-to-end arm (compiles the ladder) is
in the slow tier with the rest of the e2e suite.
"""

import json
import os

import numpy as np
import pytest

from daccord_tpu.kernels.tensorize import BatchShape, WindowBatch
from daccord_tpu.runtime.faults import (FaultDeviceLost, FaultDispatchError,
                                        FaultPlan, InjectedCrash)
from daccord_tpu.runtime.supervisor import (DEGRADED, FAILBACK, HEALTHY,
                                            DeviceSupervisor, SupervisorConfig,
                                            WatchdogTimeout, _Watchdog)
from daccord_tpu.tools.eventcheck import validate_events
from daccord_tpu.utils.obs import JsonlLogger


# ---------------------------------------------------------------- fault plan

def test_fault_plan_parse_and_semantics():
    plan = FaultPlan.parse("fetch_hang:3, dispatch_error:2,device_lost:7")
    assert [(s.kind, s.at) for s in plan.specs] == [
        ("fetch_hang", 3), ("dispatch_error", 2), ("device_lost", 7)]
    # default count is 1
    assert FaultPlan.parse("compile_stall").specs[0].at == 1
    with pytest.raises(ValueError):
        FaultPlan.parse("unknown_kind:1")
    with pytest.raises(ValueError):
        FaultPlan.parse("fetch_hang:zero")
    with pytest.raises(ValueError):
        FaultPlan.parse("fetch_hang:0")

    # dispatch_error fires on the 2nd dispatch, once
    plan = FaultPlan.parse("dispatch_error:2")
    plan.op("dispatch")
    with pytest.raises(FaultDispatchError):
        plan.op("dispatch")
    plan.op("dispatch")  # one-shot: no re-fire

    # device_lost marks the device dead for every later primary op + probe
    plan = FaultPlan.parse("device_lost:1")
    with pytest.raises(FaultDeviceLost):
        plan.op("fetch")
    assert plan.probe_override() is False
    with pytest.raises(FaultDeviceLost):
        plan.op("dispatch")
    # degraded ops never see device faults (only crash)
    plan.op("dispatch", degraded=True)

    # crash is a BaseException and fires even in degraded mode
    plan = FaultPlan.parse("crash:2")
    plan.op("dispatch", degraded=True)
    with pytest.raises(InjectedCrash):
        plan.op("fetch", degraded=True)

    assert FaultPlan.from_env(env={}) is None
    assert FaultPlan.from_env(env={"DACCORD_FAULT": "fetch_hang"}) is not None


# ---------------------------------------------------------------- watchdog

def test_watchdog_deadline_and_recovery():
    import time

    wd = _Watchdog()
    assert wd.run(lambda x: x + 1, (41,), deadline_s=5.0) == 42
    with pytest.raises(WatchdogTimeout):
        wd.run(lambda: time.sleep(5), (), deadline_s=0.1)
    # a fresh worker replaces the abandoned one; the watchdog still works
    assert wd.run(lambda: "ok", (), deadline_s=5.0) == "ok"
    # exceptions relay to the caller
    with pytest.raises(ZeroDivisionError):
        wd.run(lambda: 1 / 0, (), deadline_s=5.0)


# ---------------------------------------------------------------- stub engine

def _mini_batch(b=4, d=2, l=8):
    return WindowBatch(seqs=np.zeros((b, d, l), np.int8),
                       lens=np.zeros((b, d), np.int32),
                       nsegs=np.zeros(b, np.int32),
                       shape=BatchShape(depth=d, seg_len=l, wlen=l),
                       read_ids=np.zeros(b, np.int64),
                       wstarts=np.zeros(b, np.int64))


class StubEngine:
    """Scripted sync solver: dispatch returns a tagged handle, fetch returns
    a recognizable result dict. ``fail_dispatches`` makes the first N
    dispatch calls raise (supervisor-retry exercise without a fault plan)."""

    def __init__(self, fail_dispatches=0):
        self.n_dispatch = 0
        self.n_fetch = 0
        self.fail_dispatches = fail_dispatches

    def dispatch(self, batch):
        self.n_dispatch += 1
        if self.n_dispatch <= self.fail_dispatches:
            raise RuntimeError("stub dispatch failure")
        return ("stub", self.n_dispatch, batch)

    def fetch(self, h):
        self.n_fetch += 1
        return {"engine": "stub", "dispatch_no": h[1]}


def _fallback_result(batch):
    return {"engine": "fallback"}


def _sup(engine, tmp_path, name, faults=None, probe=None, fallback=True,
         **cfg_kw):
    cfg_kw.setdefault("backoff_base_s", 0.01)
    cfg_kw.setdefault("op_deadline_s", 10.0)
    ev = os.path.join(str(tmp_path), f"{name}.events.jsonl")
    sup = DeviceSupervisor(
        engine.dispatch, engine.fetch, None,
        fallback_factory=(lambda: _fallback_result) if fallback else None,
        log=JsonlLogger(ev), cfg=SupervisorConfig(**cfg_kw),
        faults=faults, probe_fn=probe, describe="stub")
    return sup, ev


def test_supervisor_dispatch_error_retries(tmp_path, monkeypatch):
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    eng = StubEngine()
    sup, ev = _sup(eng, tmp_path, "derr",
                   faults=FaultPlan.parse("dispatch_error:2"),
                   probe=lambda: True)
    b = _mini_batch()
    out = sup.fetch(sup.dispatch(b))
    assert out["engine"] == "stub"
    # 2nd dispatch: injected error -> probe alive -> retry succeeds
    out = sup.fetch(sup.dispatch(b))
    assert out["engine"] == "stub"
    assert sup.state == HEALTHY and not sup.failed_over
    assert sup.counters["retries"] == 1
    events = [json.loads(x)["event"] for x in open(ev)]
    assert "sup_retry" in events and "sup_failover" not in events
    assert validate_events(ev, strict=True) == []


def test_supervisor_fetch_hang_redispatches(tmp_path, monkeypatch):
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    eng = StubEngine()
    sup, ev = _sup(eng, tmp_path, "hang",
                   faults=FaultPlan.parse("fetch_hang:1"),
                   probe=lambda: True)
    out = sup.fetch(sup.dispatch(_mini_batch()))
    # the hung fetch was abandoned and its batch re-dispatched: exactly one
    # result reaches the caller (no duplicate, no drop)
    assert out["engine"] == "stub" and out["dispatch_no"] == 2
    assert eng.n_dispatch == 2 and eng.n_fetch == 1
    assert sup.counters["timeouts"] == 1 and sup.state == HEALTHY
    # sup_fault records the spec's kind and its own-domain index (1st fetch),
    # not the exception class name or the combined device-op counter
    faults = [json.loads(x) for x in open(ev)]
    faults = [r for r in faults if r["event"] == "sup_fault"]
    assert faults == [{"t": faults[0]["t"], "ts": faults[0]["ts"],
                       "event": "sup_fault",
                       "kind": "fetch_hang", "op": "fetch", "n": 1}]
    assert validate_events(ev, strict=True) == []


def test_supervisor_compile_classification(tmp_path, monkeypatch):
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    eng = StubEngine()
    sup, ev = _sup(eng, tmp_path, "compile",
                   faults=FaultPlan.parse("compile_stall"))
    sup.fetch(sup.dispatch(_mini_batch()))     # cold shape
    sup.fetch(sup.dispatch(_mini_batch()))     # warm now
    recs = [json.loads(x) for x in open(ev)]
    compiles = [r for r in recs if r["event"] == "sup_compile"]
    assert len(compiles) == 1 and compiles[0]["key"].endswith("B4xD2xL8")
    # the injected stall produced a heartbeat, then the op proceeded
    assert any(r["event"] == "sup_heartbeat" for r in recs)
    states = [(r["state_from"], r["state_to"]) for r in recs
              if r["event"] == "sup_state"]
    assert ("HEALTHY", "COMPILING") in states and ("COMPILING", "HEALTHY") in states
    assert validate_events(ev, strict=True) == []
    # the fingerprint registry made the second dispatch warm — and persists
    from daccord_tpu.utils.obs import fingerprint_seen

    assert fingerprint_seen("B4xD2xL8")


def test_supervisor_device_lost_failover_and_replay(tmp_path, monkeypatch):
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    eng = StubEngine()
    sup, ev = _sup(eng, tmp_path, "lost",
                   faults=FaultPlan.parse("device_lost:3"))
    h1 = sup.dispatch(_mini_batch())           # op 1: ok
    h2 = sup.dispatch(_mini_batch())           # op 2: ok (in flight)
    h3 = sup.dispatch(_mini_batch())           # op 3: device lost
    assert sup.failed_over and sup.state == DEGRADED
    # the batch whose dispatch died AND the still-in-flight handles all
    # replay on the fallback engine
    assert sup.fetch(h3)["engine"] == "fallback"
    assert sup.fetch(h1)["engine"] == "fallback"
    assert sup.fetch(h2)["engine"] == "fallback"
    # later dispatches never touch the dead primary
    nd = eng.n_dispatch
    assert sup.fetch(sup.dispatch(_mini_batch()))["engine"] == "fallback"
    assert eng.n_dispatch == nd
    recs = [json.loads(x) for x in open(ev)]
    chain = [(r["state_from"], r["state_to"]) for r in recs
             if r["event"] == "sup_state"]
    assert ("SUSPECT", "LOST") in chain and ("LOST", "DEGRADED") in chain
    assert all("ts" in r for r in recs if r["event"] == "sup_state")
    assert validate_events(ev, strict=True) == []


def test_supervisor_failback(tmp_path, monkeypatch):
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    alive = {"v": False}
    eng = StubEngine(fail_dispatches=1)
    sup, ev = _sup(eng, tmp_path, "failback", probe=lambda: alive["v"],
                   failback=True, failback_probe_s=0.0, max_retries=0)
    # primary fails, probe says dead -> degraded
    out = sup.fetch(sup.dispatch(_mini_batch()))
    assert out["engine"] == "fallback" and sup.state == DEGRADED
    # chip revives: next dispatch re-probes, fails back to the primary
    alive["v"] = True
    out = sup.fetch(sup.dispatch(_mini_batch()))
    assert out["engine"] == "stub"
    assert sup.state == HEALTHY
    recs = [json.loads(x) for x in open(ev)]
    assert any(r["event"] == "sup_failback" for r in recs)
    chain = [(r["state_from"], r["state_to"]) for r in recs
             if r["event"] == "sup_state"]
    # failback re-compiles shapes, so the path back is FAILBACK -> COMPILING
    # -> HEALTHY
    assert ("DEGRADED", "FAILBACK") in chain
    assert chain[-1][1] == "HEALTHY"
    assert validate_events(ev, strict=True) == []


def test_supervisor_second_loss_after_failback(tmp_path, monkeypatch):
    """A chip that dies AGAIN after a successful failback must re-enter
    DEGRADED (cached fallback re-engaged) — not leave the supervisor stuck
    retrying the dead primary from SUSPECT on every later dispatch."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    alive = {"v": False}

    class FlakyEngine(StubEngine):
        def __init__(self):
            super().__init__()
            self.up = False

        def dispatch(self, batch):
            self.n_dispatch += 1
            if not self.up:
                raise RuntimeError("chip down")
            return ("stub", self.n_dispatch, batch)

    eng = FlakyEngine()
    sup, ev = _sup(eng, tmp_path, "reloss", probe=lambda: alive["v"],
                   failback=True, failback_probe_s=0.0, max_retries=0)
    assert sup.fetch(sup.dispatch(_mini_batch()))["engine"] == "fallback"
    # revive -> failback -> healthy primary
    alive["v"] = True
    eng.up = True
    assert sup.fetch(sup.dispatch(_mini_batch()))["engine"] == "stub"
    assert sup.state == HEALTHY
    # second death: back to the (cached) fallback, state DEGRADED again
    alive["v"] = False
    eng.up = False
    assert sup.fetch(sup.dispatch(_mini_batch()))["engine"] == "fallback"
    assert sup.state == DEGRADED
    # and later dispatches do NOT retry the dead primary
    nd = eng.n_dispatch
    assert sup.fetch(sup.dispatch(_mini_batch()))["engine"] == "fallback"
    assert eng.n_dispatch == nd
    assert validate_events(ev, strict=True) == []


def test_supervisor_no_fallback_raises(tmp_path, monkeypatch):
    from daccord_tpu.runtime.supervisor import DeviceLostError

    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    eng = StubEngine()
    sup, _ = _sup(eng, tmp_path, "nofb",
                  faults=FaultPlan.parse("device_lost:1"), fallback=False)
    with pytest.raises(DeviceLostError):
        sup.dispatch(_mini_batch())

    # a fallback FACTORY that fails (e.g. native library not built on a
    # device host) surfaces as the same classified loss, not a stray error
    def broken_factory():
        raise RuntimeError("native library unavailable")

    sup2 = DeviceSupervisor(
        eng.dispatch, eng.fetch, None, fallback_factory=broken_factory,
        log=JsonlLogger(None), cfg=SupervisorConfig(backoff_base_s=0.01),
        faults=FaultPlan.parse("device_lost:1"))
    with pytest.raises(DeviceLostError, match="fallback engine"):
        sup2.dispatch(_mini_batch())


# ---------------------------------------------------------------- eventcheck

def test_eventcheck_schema_and_transitions(tmp_path):
    good = tmp_path / "good.jsonl"
    good.write_text("\n".join([
        json.dumps({"t": 0.1, "ts": 1.0, "event": "sup_init", "primary": "x",
                    "op_deadline_s": 1.0, "compile_deadline_s": 2.0}),
        json.dumps({"t": 0.2, "ts": 1.1, "event": "sup_state",
                    "state_from": "HEALTHY",
                    "state_to": "SUSPECT", "reason": "r"}),
        json.dumps({"t": 0.3, "ts": 1.2, "event": "sup_state",
                    "state_from": "SUSPECT",
                    "state_to": "LOST", "reason": "r"}),
        json.dumps({"t": 0.4, "ts": 1.3, "event": "sup_state",
                    "state_from": "LOST",
                    "state_to": "DEGRADED", "reason": "r"}),
        json.dumps({"t": 0.5, "ts": 1.4, "event": "custom_info",
                    "anything": 1}),
    ]) + "\n")
    assert validate_events(str(good), strict=True) == []

    bad = tmp_path / "bad.jsonl"
    bad.write_text("\n".join([
        "not json at all",
        json.dumps({"event": "sup_retry"}),                      # missing t + fields
        json.dumps({"t": 1.0, "event": "sup_state", "state_from": "HEALTHY",
                    "state_to": "DEGRADED", "reason": "r", "ts": 1.0}),
        json.dumps({"t": 0.5, "event": "batch", "windows": "many",
                    "solved": 1}),                               # wrong type
    ]) + "\n")
    errs = validate_events(str(bad), strict=True)
    assert len(errs) >= 4
    assert any("illegal transition" in e for e in errs)

    # two appended supervisor lifecycles (rerun against the same --events
    # path): sup_init is a stream boundary, so the restarted clock and state
    # chain are legal under --strict
    two = tmp_path / "two.jsonl"
    two.write_text(good.read_text() + good.read_text())
    assert validate_events(str(two), strict=True) == []

    from daccord_tpu.tools.eventcheck import eventcheck_main

    assert eventcheck_main([str(good), "--strict"]) == 0
    assert eventcheck_main([str(bad)]) == 1


def test_expected_compile_wall_matches_measured_scaling():
    from daccord_tpu.utils.obs import expected_compile_wall_s

    # anchored on the r5 measurements: 1024 -> 242 s, 2048 -> 925 s
    assert expected_compile_wall_s(1024) == pytest.approx(242, rel=0.05)
    assert expected_compile_wall_s(2048) == pytest.approx(925, rel=0.10)
    assert expected_compile_wall_s(0) > 0
    assert expected_compile_wall_s(1 << 20) <= 4 * 3600


# ------------------------------------------------------------ e2e (native)

@pytest.fixture(scope="module")
def native_dataset(tmp_path_factory):
    native = pytest.importorskip("daccord_tpu.native")
    if not native.available():
        pytest.skip("native library unavailable")
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path_factory.mktemp("sup_e2e"))
    cfg = SimConfig(genome_len=1500, coverage=12, read_len_mean=500,
                    min_overlap=200, seed=7)
    return make_dataset(d, cfg, name="p"), d


def _native_cfg(ev=None, **kw):
    from daccord_tpu.runtime import PipelineConfig

    return PipelineConfig(batch_size=64, native_solver=True, events_path=ev,
                          **kw)


def _run(out, d, name, ev=None, **kw):
    from daccord_tpu.runtime import correct_to_fasta

    fasta = os.path.join(d, f"{name}.fasta")
    stats = correct_to_fasta(out["db"], out["las"], fasta, _native_cfg(ev, **kw))
    return fasta, stats


def test_e2e_device_lost_byte_parity(native_dataset, monkeypatch):
    """ISSUE acceptance: DACCORD_FAULT=device_lost:N -> the run completes in
    degraded mode, byte-identical FASTA, and the events file records the
    HEALTHY->...->LOST->DEGRADED transitions with timestamps."""
    out, d = native_dataset
    f0, s0 = _run(out, d, "base")
    assert not s0.degraded

    monkeypatch.setenv("DACCORD_FAULT", "device_lost:3")
    ev = os.path.join(d, "lost.events.jsonl")
    f1, s1 = _run(out, d, "lost", ev=ev)
    assert s1.degraded and "device_lost" in s1.fallback_reason
    assert open(f0).read() == open(f1).read()

    assert validate_events(ev, strict=True) == []
    recs = [json.loads(x) for x in open(ev)]
    chain = [(r["state_from"], r["state_to"]) for r in recs
             if r["event"] == "sup_state"]
    assert ("SUSPECT", "LOST") in chain and ("LOST", "DEGRADED") in chain
    assert all(r["ts"] > 0 for r in recs if r["event"] == "sup_state")
    done = [r for r in recs if r["event"] == "sup_done"]
    assert done and done[0]["degraded"] and done[0]["state"] == "DEGRADED"


def test_e2e_fetch_hang_retry_recovers(native_dataset, monkeypatch):
    """fetch_hang: retry-then-recover with no duplicate/dropped windows
    (byte-identical output proves both at once)."""
    out, d = native_dataset
    f0, _ = _run(out, d, "base2")
    monkeypatch.setenv("DACCORD_FAULT", "fetch_hang:2")
    monkeypatch.setenv("DACCORD_SUP_BACKOFF_S", "0.01")
    ev = os.path.join(d, "hang.events.jsonl")
    f1, s1 = _run(out, d, "hang", ev=ev)
    assert not s1.degraded          # recovered, never failed over
    assert open(f0).read() == open(f1).read()
    recs = [json.loads(x) for x in open(ev)]
    assert any(r["event"] == "sup_retry" for r in recs)
    assert validate_events(ev, strict=True) == []


def test_e2e_dispatch_error_retry_recovers(native_dataset, monkeypatch):
    out, d = native_dataset
    f0, _ = _run(out, d, "base3")
    monkeypatch.setenv("DACCORD_FAULT", "dispatch_error:4")
    monkeypatch.setenv("DACCORD_SUP_BACKOFF_S", "0.01")
    f1, s1 = _run(out, d, "derr")
    assert not s1.degraded
    assert open(f0).read() == open(f1).read()


def test_e2e_checkpoint_failover_compose(native_dataset, monkeypatch):
    """Checkpoint + failover compose: device loss, then a hard crash, then a
    resume — the resumed run completes and its FASTA is byte-identical to an
    uninterrupted shard."""
    from daccord_tpu.parallel.launch import run_shard, shard_paths
    from daccord_tpu.runtime import PipelineConfig

    out, d = native_dataset
    # single bucket + small batch: reads finalize (and checkpoint) steadily,
    # so the injected crash reliably lands after a checkpoint exists
    cfg = PipelineConfig(batch_size=32, native_solver=True,
                         depth_buckets=(), bucket_flush_reads=4)

    ref_dir = os.path.join(d, "ref_out")
    m_ref = run_shard(out["db"], out["las"], ref_dir, 0, 1, cfg,
                      checkpoint_every=2)
    assert not m_ref.get("degraded")
    ref_fasta = open(shard_paths(ref_dir, 0)["fasta"]).read()

    crash_dir = os.path.join(d, "crash_out")
    monkeypatch.setenv("DACCORD_FAULT", "device_lost:2,crash:14")
    with pytest.raises(InjectedCrash):
        run_shard(out["db"], out["las"], crash_dir, 0, 1, cfg,
                  checkpoint_every=2)
    paths = shard_paths(crash_dir, 0)
    assert os.path.exists(paths["progress"])      # died mid-shard, after ckpt
    assert not os.path.exists(paths["manifest"])

    monkeypatch.delenv("DACCORD_FAULT")
    m = run_shard(out["db"], out["las"], crash_dir, 0, 1, cfg,
                  checkpoint_every=2)
    assert m["resumed_at_read"] > 0
    assert open(paths["fasta"]).read() == ref_fasta
    assert not os.path.exists(paths["progress"])  # cleaned after manifest


# ------------------------------------------------------------ e2e (JAX ladder)

@pytest.mark.slow
def test_e2e_jax_ladder_device_lost_byte_parity(native_dataset, monkeypatch):
    """Default JAX-CPU ladder primary: device loss fails over to the exact
    same-ladder host fallback (failover_backend auto resolves to 'cpu' on a
    cpu platform) — byte-identical output through the real device-batch
    path."""
    from daccord_tpu.runtime import PipelineConfig, correct_to_fasta

    out, d = native_dataset
    f0 = os.path.join(d, "jax_base.fasta")
    s0 = correct_to_fasta(out["db"], out["las"], f0,
                          PipelineConfig(batch_size=128))
    assert not s0.degraded
    monkeypatch.setenv("DACCORD_FAULT", "device_lost:4")
    ev = os.path.join(d, "jax.events.jsonl")
    f1 = os.path.join(d, "jax_lost.fasta")
    s1 = correct_to_fasta(out["db"], out["las"], f1,
                          PipelineConfig(batch_size=128, events_path=ev))
    assert s1.degraded
    assert open(f0).read() == open(f1).read()
    assert validate_events(ev, strict=True) == []
