"""Oracle consensus: alignment primitives, profile estimation, Q-score uplift."""

import numpy as np
import pytest

from daccord_tpu.oracle import (
    ConsensusConfig,
    correct_read,
    cut_windows,
    edit_distance,
    estimate_profile_two_pass,
    infix_distance,
    make_offset_likely,
    refine_overlap,
    solve_window,
)
from daccord_tpu.oracle.profile import ErrorProfile, OffsetLikely
from daccord_tpu.sim import SimConfig, simulate
from daccord_tpu.utils import revcomp_ints, seq_to_ints


def _brute_ed(a, b):
    n, m = len(a), len(b)
    D = np.zeros((n + 1, m + 1), dtype=int)
    D[0] = np.arange(m + 1)
    D[:, 0] = np.arange(n + 1)
    for i in range(1, n + 1):
        for j in range(1, m + 1):
            D[i, j] = min(D[i - 1, j - 1] + (a[i - 1] != b[j - 1]), D[i - 1, j] + 1, D[i, j - 1] + 1)
    return D[n, m]


def test_edit_distance_matches_bruteforce():
    rng = np.random.default_rng(3)
    for _ in range(25):
        a = rng.integers(0, 4, rng.integers(0, 40), np.int8)
        b = rng.integers(0, 4, rng.integers(0, 40), np.int8)
        assert edit_distance(a, b) == _brute_ed(a, b)


def test_infix_distance():
    hay = seq_to_ints("ACGTACGTACGTTTTACGT")
    assert infix_distance(seq_to_ints("GTACGT"), hay) == 0
    assert infix_distance(seq_to_ints("GTACCT"), hay) == 1
    assert infix_distance(np.zeros(0, np.int8), hay) == 0


def test_offset_likely_shape_and_drift():
    prof = ErrorProfile(p_ins=0.08, p_del=0.04, p_sub=0.015)
    ol = OffsetLikely(prof, positions=40, max_offset=56)
    assert ol.table.shape == (40, 56)
    np.testing.assert_allclose(ol.table.sum(axis=1), 1.0, atol=1e-3)
    # positive drift: mean offset at position 30 should exceed 30
    mean30 = (ol.table[30] * np.arange(56)).sum()
    assert 30.0 < mean30 < 33.0


@pytest.fixture(scope="module")
def pile_fixture():
    cfg = SimConfig(genome_len=3000, coverage=18, read_len_mean=900, seed=7)
    res = simulate(cfg)
    # choose a read comfortably inside the genome
    aread = max(range(len(res.reads)),
                key=lambda i: min(res.reads[i].start, cfg.genome_len - res.reads[i].end) > 200 and len(res.reads[i].seq) or 0)
    pile = [o for o in res.overlaps if o.aread == aread]
    a = res.reads[aread].seq
    refined = [refine_overlap(o, a, res.reads[o.bread].seq, cfg.tspace) for o in pile]
    return cfg, res, aread, a, refined


def test_refine_overlap_maps_are_monotone(pile_fixture):
    _, _, _, _, refined = pile_fixture
    for r in refined[:10]:
        assert np.all(np.diff(r.a2b) >= 0)
        assert r.a2b[0] == r.ovl.bbpos and r.a2b[-1] == r.ovl.bepos


def test_profile_estimation(pile_fixture):
    cfg, _, _, a, refined = pile_fixture
    ccfg = ConsensusConfig()
    windows = cut_windows(a, refined, w=ccfg.w, adv=ccfg.adv)
    prof = estimate_profile_two_pass(refined, windows, ccfg, sample=24)
    # within a factor ~2 of the generative rates
    assert 0.03 < prof.p_ins < 0.16
    assert 0.015 < prof.p_del < 0.09
    assert prof.p_sub < 0.06


def test_qscore_uplift(pile_fixture):
    cfg, res, aread, a, refined = pile_fixture
    ccfg = ConsensusConfig()
    windows = cut_windows(a, refined, w=ccfg.w, adv=ccfg.adv)
    prof = estimate_profile_two_pass(refined, windows, ccfg, sample=24)
    ols = make_offset_likely(prof, ccfg)
    corr = correct_read(a, windows, ols, ccfg)
    assert corr.n_solved / corr.n_windows > 0.9

    r = res.reads[aread]
    truth = res.genome[r.start : r.end]
    if r.strand == 1:
        truth = revcomp_ints(truth)
    raw_err = edit_distance(r.seq, truth) / len(truth)
    tot_e = sum(infix_distance(f, truth) for f in corr.fragments)
    tot_l = sum(len(f) for f in corr.fragments)
    assert tot_l > 0.9 * len(truth)
    corr_err = tot_e / tot_l
    # >= 10x error-rate reduction (about +10 Q)
    assert corr_err < raw_err / 10, (corr_err, raw_err)


def test_unsolved_window_splits_or_patches(pile_fixture):
    """A window with no segments must split the read in split mode and be
    patched with raw bases in patch mode."""
    cfg, res, aread, a, refined = pile_fixture
    ccfg = ConsensusConfig()
    windows = cut_windows(a, refined, w=ccfg.w, adv=ccfg.adv)
    prof = estimate_profile_two_pass(refined, windows, ccfg, sample=16)
    ols = make_offset_likely(prof, ccfg)
    # poison the middle window
    mid = len(windows) // 2
    windows[mid].segments = []
    corr = correct_read(a, windows, ols, ccfg)
    assert len(corr.fragments) >= 2

    ccfg2 = ConsensusConfig(mode="patch")
    corr2 = correct_read(a, windows, ols, ccfg2)
    assert len(corr2.fragments) == 1


def test_stitch_long_read_linear_time():
    """ONT-scale stitching: 20k windows of a 200kb read stitch in seconds
    (the piece-list accumulator is O(read length), not O(read length^2))."""
    import time

    from daccord_tpu.oracle.consensus import ConsensusConfig, stitch_results

    rng = np.random.default_rng(3)
    rlen = 200_000
    a = rng.integers(0, 4, rlen).astype(np.int8)
    w, adv = 40, 10
    nwin = (rlen - w) // adv + 1
    rows = []
    for i in range(nwin):
        ws = i * adv
        seq = a[ws : ws + w].copy()
        if rng.random() < 0.002:
            rows.append((ws, w, None))         # occasional unsolved window
        else:
            rows.append((ws, w, seq))
    t0 = time.perf_counter()
    frags = stitch_results(a, rows, ConsensusConfig(mode="patch"))
    dt = time.perf_counter() - t0
    assert len(frags) == 1
    assert abs(len(frags[0]) - rlen) < 100
    assert dt < 30, f"stitching 20k windows took {dt:.1f}s"


def test_profile_decollapse_accuracy(pile_fixture):
    """The de-collapse correction in profile_vs_consensus recovers the
    generative rates to ~20% relative error; the uncorrected unit-cost op
    counts misattribute ~half the deletions as substitutions (a deletion
    with an insertion within ~2 positions aligns as one substitution)."""
    cfg, _, _, a, refined = pile_fixture
    ccfg = ConsensusConfig()
    windows = cut_windows(a, refined, w=ccfg.w, adv=ccfg.adv)
    prof = estimate_profile_two_pass(refined, windows, ccfg, sample=32)
    assert abs(prof.p_ins - cfg.p_ins) / cfg.p_ins < 0.25
    assert abs(prof.p_del - cfg.p_del) / cfg.p_del < 0.35
    # residual sub inflation comes from consensus errors; it must at least
    # be far below the uncorrected ~2.3x over-estimate
    assert prof.p_sub < 2.0 * cfg.p_sub


